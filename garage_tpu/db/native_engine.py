"""`db_engine = "native"` — the C++ metadata engine (_native/kvlog.cpp)
behind the generic Db/Tree/Tx interface.

Fills the reference's LMDB slot (src/db/lmdb_adapter.rs) with native-speed
point ops and range scans: the keyspace lives in C++ ordered maps, every
commit is one crc-framed append to a write-ahead log, recovery truncates
torn tails, compaction bounds the log.  The WAL format is byte-identical
to the Python log engine (db/log_engine.py) — a store written by either
opens in the other, so switching engines needs no convert-db.

Binding: the CPython C-API module (garage_kv.so, _native/kvpy.cpp) when it
builds — ~100 ns per call — with a ctypes fallback (~3 us per call) so a
missing Python.h degrades speed, never correctness.

Transactions keep the log engine's overlay design: buffered writes with
read-your-writes, then the whole batch becomes ONE native commit (one
frame, atomic by construction).
"""

from __future__ import annotations

import os
import shutil
import struct
from typing import Callable, Iterator, TypeVar

from . import Db, Tree, Tx, TxAbort
from .log_engine import _DEL, _PUT, _enc_record

T = TypeVar("T")

_ITER_BUF = 256 * 1024  # per-chunk scan buffer (grown when a value exceeds it)


class NativeUnavailable(RuntimeError):
    pass


class _CtypesBinding:
    """kv_* via ctypes, shaped like the garage_kv extension module."""

    def __init__(self, l):
        import ctypes

        self._ct = ctypes
        self._l = l

    def open(self, path: str, sync_mode: int) -> int:
        h = self._l.kv_open(path.encode(), int(sync_mode))
        if not h:
            raise OSError(f"cannot open native kv log at {path!r}")
        return h

    def close(self, h) -> None:
        self._l.kv_close(h)

    def sync_barrier(self, h) -> None:
        if self._l.kv_sync_barrier(h) != 0:
            raise OSError("native kv sync barrier failed")

    def commit(self, h, payload: bytes) -> None:
        rc = self._l.kv_commit(h, payload, len(payload))
        if rc != 0:
            raise OSError(f"native kv commit failed (rc={rc})")

    def get(self, h, tree: bytes, key: bytes) -> bytes | None:
        ct = self._ct
        out = ct.c_void_p()
        outlen = ct.c_size_t()
        found = self._l.kv_get(
            h, tree, len(tree), key, len(key), ct.byref(out), ct.byref(outlen)
        )
        if not found:
            return None
        return ct.string_at(out.value, outlen.value)

    def tree_len(self, h, tree: bytes) -> int:
        return self._l.kv_tree_len(h, tree, len(tree))

    def tree_names(self, h) -> bytes:
        ct = self._ct
        cap = 4096
        while True:
            buf = ct.create_string_buffer(cap)
            need = self._l.kv_tree_names(h, buf, cap)
            if need <= cap:
                return buf.raw[:need]
            cap = need

    def iter_chunk(
        self, h, tree: bytes, start, end, reverse: bool, max_items: int, cap: int
    ) -> tuple[bytes, bool]:
        ct = self._ct
        buf = ct.create_string_buffer(cap)
        done = ct.c_int(0)
        n = self._l.kv_iter_chunk(
            h, tree, len(tree),
            start, len(start) if start is not None else 0,
            1 if start is not None else 0,
            end, len(end) if end is not None else 0,
            1 if end is not None else 0,
            1 if reverse else 0,
            max_items, buf, cap, ct.byref(done),
        )
        return buf.raw[:n], bool(done.value)

    def compact(self, h) -> None:
        if self._l.kv_compact_now(h) != 0:
            raise OSError("native kv compaction failed")

    def log_bytes(self, h) -> int:
        return self._l.kv_log_bytes(h)

    def live_bytes(self, h) -> int:
        return self._l.kv_live_bytes(h)

    def sync_failures(self, h) -> int:
        if not hasattr(self._l, "kv_sync_failures"):
            return 0  # older externally-built .so without the symbol
        return self._l.kv_sync_failures(h)


def _binding():
    from .. import _native

    kv = _native.kv_module()
    if kv is not None:
        return kv
    l = _native.lib()
    if l is None:
        raise NativeUnavailable(
            "native library unavailable (g++ build failed?)"
        )
    return _CtypesBinding(l)


class NativeTree(Tree):
    __slots__ = ("db", "name", "_bname")

    def __init__(self, db: "NativeDb", name: str):
        self.db = db
        self.name = name
        self._bname = name.encode()

    def get(self, k: bytes) -> bytes | None:
        return self.db.kv.get(self.db.h, self._bname, bytes(k))

    def insert(self, k: bytes, v: bytes) -> None:
        self.db._autocommit(_enc_record(_PUT, self.name, bytes(k), bytes(v)))

    def remove(self, k: bytes) -> None:
        self.db._autocommit(_enc_record(_DEL, self.name, bytes(k), None))

    def __len__(self) -> int:
        return self.db.kv.tree_len(self.db.h, self._bname)

    def iter_range(
        self,
        start: bytes | None = None,
        end: bytes | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]:
        return self.db._iter(self._bname, start, end, reverse)

    def first(self) -> tuple[bytes, bytes] | None:
        for kv in self.db._iter(self._bname, None, None, False, max_items=1):
            return kv
        return None

    def get_gt(self, k: bytes) -> tuple[bytes, bytes] | None:
        it = self.db._iter(
            self._bname, bytes(k) + b"\x00", None, False, max_items=1
        )
        for kv in it:
            return kv
        return None


class NativeTx(Tx):
    """Overlay transaction: same semantics as log_engine.LogTx."""

    def __init__(self, db: "NativeDb"):
        self.db = db
        self.writes: dict[tuple[str, bytes], tuple[int, bytes | None]] = {}
        self.order: list[bytes] = []  # encoded records, commit order

    def get(self, tree: NativeTree, k: bytes) -> bytes | None:
        ent = self.writes.get((tree.name, bytes(k)))
        if ent is not None:
            return ent[1]
        return tree.get(k)

    def insert(self, tree: NativeTree, k: bytes, v: bytes) -> None:
        k, v = bytes(k), bytes(v)
        self.writes[(tree.name, k)] = (_PUT, v)
        self.order.append(_enc_record(_PUT, tree.name, k, v))

    def remove(self, tree: NativeTree, k: bytes) -> None:
        k = bytes(k)
        self.writes[(tree.name, k)] = (_DEL, None)
        self.order.append(_enc_record(_DEL, tree.name, k, None))

    def len(self, tree: NativeTree) -> int:
        n = len(tree)
        for (tname, k), (op, _v) in self.writes.items():
            if tname != tree.name:
                continue
            present = tree.get(k) is not None
            if op == _PUT and not present:
                n += 1
            elif op == _DEL and present:
                n -= 1
        return n


class NativeDb(Db):
    engine = "native"

    def __init__(self, path: str, fsync: bool | str = True, binding=None):
        """`fsync` selects the durability mode: True = fdatasync inside
        every commit; "group" = group commit (commits ack immediately, a
        C++ flusher thread runs fdatasync continuously — durability
        window ~ one fdatasync, same class as sqlite WAL+NORMAL and the
        reference's default metadata_fsync=false LMDB posture;
        `sync_barrier()` forces full durability); False = sync only at
        compaction/close.

        `binding` overrides the kv backend (an object shaped like the
        garage_kv module) — used by the sanitizer job to force the ctypes
        path against an instrumented .so."""
        self.kv = binding if binding is not None else _binding()
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        mode = 2 if fsync == "group" else (1 if fsync else 0)
        self.h = self.kv.open(path, mode)
        self.trees: dict[str, NativeTree] = {}
        self._in_tx = False
        for name in self._native_tree_names():
            self.trees[name] = NativeTree(self, name)

    # --- helpers --------------------------------------------------------------

    def _iter(
        self,
        bname: bytes,
        start: bytes | None,
        end: bytes | None,
        reverse: bool,
        max_items: int = 0,
    ) -> Iterator[tuple[bytes, bytes]]:
        cap = _ITER_BUF
        unpack = struct.unpack_from
        while True:
            chunk, done = self.kv.iter_chunk(
                self.h, bname, start, end, reverse, max_items, cap
            )
            n = len(chunk)
            if n == 0 and not done:
                cap *= 2  # one entry exceeds the buffer
                continue
            pos = 0
            last = None
            while pos < n:
                (klen,) = unpack("<I", chunk, pos)
                k = chunk[pos + 4 : pos + 4 + klen]
                pos += 4 + klen
                (vlen,) = unpack("<I", chunk, pos)
                v = chunk[pos + 4 : pos + 4 + vlen]
                pos += 4 + vlen
                last = k
                yield (k, v)
            if done or last is None:
                return
            if max_items:
                return  # caller asked for a bounded prefix only
            if reverse:
                end = last  # exclusive upper bound for the next chunk
            else:
                start = last + b"\x00"

    def _native_tree_names(self) -> list[str]:
        raw = self.kv.tree_names(self.h)
        names, pos = [], 0
        while pos < len(raw):
            (n,) = struct.unpack_from("<H", raw, pos)
            names.append(raw[pos + 2 : pos + 2 + n].decode())
            pos += 2 + n
        return names

    def _autocommit(self, payload: bytes) -> None:
        if self._in_tx:
            raise RuntimeError(
                "direct tree mutation inside a transaction; use the tx handle"
            )
        self.kv.commit(self.h, payload)

    # --- Db interface ---------------------------------------------------------

    def open_tree(self, name: str) -> NativeTree:
        t = self.trees.get(name)
        if t is None:
            t = self.trees[name] = NativeTree(self, name)
        return t

    def list_trees(self) -> list[str]:
        return sorted(set(self.trees) | set(self._native_tree_names()))

    def transaction(self, fn: Callable[[Tx], T]) -> T:
        self._in_tx = True
        tx = NativeTx(self)
        try:
            res = fn(tx)
        except TxAbort as e:
            return e.value
        finally:
            self._in_tx = False
        if tx.order:
            self.kv.commit(self.h, b"".join(tx.order))
        return res

    def sync_barrier(self) -> None:
        """Block until every acknowledged commit is on stable storage
        (group mode waits out the flusher; other modes fdatasync)."""
        self.kv.sync_barrier(self.h)

    def snapshot(self, to_dir: str) -> None:
        os.makedirs(to_dir, exist_ok=True)
        self.kv.compact(self.h)
        shutil.copy2(
            self.path, os.path.join(to_dir, os.path.basename(self.path))
        )

    def close(self) -> None:
        if getattr(self, "h", None):
            self.kv.close(self.h)
            self.h = None
