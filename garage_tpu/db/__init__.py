"""Metadata KV-store abstraction.

Mirrors reference src/db/lib.rs:28-121 (`IDb` / `ITx` trait objects): named
trees of (bytes → bytes) with ordered range iteration and cross-tree
transactions.  Engines (the reference ships LMDB + SQLite,
src/db/lmdb_adapter.rs + sqlite_adapter.rs): `sqlite` (stdlib), `log` — a
durable log-structured engine filling the LMDB slot (log_engine.py), and
`memory` for tests/ephemeral nodes.  The same test suite runs against every
engine (reference src/db/test.rs:127-144 pattern).
"""

from __future__ import annotations

from typing import Callable, Iterator, TypeVar

T = TypeVar("T")


class TxAbort(Exception):
    """Raise inside a transaction closure to roll back and return a value."""

    def __init__(self, value=None):
        super().__init__("transaction aborted")
        self.value = value


class Tx:
    """Transaction handle: atomic get/insert/remove across trees."""

    def get(self, tree: "Tree", k: bytes) -> bytes | None:
        raise NotImplementedError

    def insert(self, tree: "Tree", k: bytes, v: bytes) -> None:
        raise NotImplementedError

    def remove(self, tree: "Tree", k: bytes) -> None:
        raise NotImplementedError

    def len(self, tree: "Tree") -> int:
        raise NotImplementedError


class Tree:
    """A named ordered keyspace; all single ops are auto-committed."""

    name: str

    def get(self, k: bytes) -> bytes | None:
        raise NotImplementedError

    def insert(self, k: bytes, v: bytes) -> None:
        raise NotImplementedError

    def remove(self, k: bytes) -> None:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def iter_range(
        self,
        start: bytes | None = None,
        end: bytes | None = None,
        reverse: bool = False,
    ) -> Iterator[tuple[bytes, bytes]]:
        """Iterate (k, v) with start <= k < end (end exclusive), ordered.

        Consistency contract (the WEAKEST the engines provide, so callers
        must assume it): keys inserted/deleted by OTHER transactions while
        the iterator is live MAY or MAY NOT be observed — the log engine
        snapshots the key range up front, the native engine pages through
        the live map in chunks, sqlite depends on statement caching.  A
        caller that mutates ahead of its own cursor (merkle/GC workers
        queue work instead) must not rely on seeing — or not seeing —
        those keys.  Pinned by tests/test_db.py
        test_iter_range_mid_iteration_contract."""
        raise NotImplementedError

    def iter_prefix(self, prefix: bytes) -> Iterator[tuple[bytes, bytes]]:
        end = _prefix_end(prefix)
        return self.iter_range(prefix, end)

    def first(self) -> tuple[bytes, bytes] | None:
        for kv in self.iter_range():
            return kv
        return None

    def get_gt(self, k: bytes) -> tuple[bytes, bytes] | None:
        """First entry with key strictly greater than k."""
        for kk, vv in self.iter_range(start=k + b"\x00"):
            return (kk, vv)
        return None


class Db:
    engine: str

    def open_tree(self, name: str) -> Tree:
        raise NotImplementedError

    def list_trees(self) -> list[str]:
        raise NotImplementedError

    def transaction(self, fn: Callable[[Tx], T]) -> T:
        """Run `fn(tx)`; commit on return, rollback on exception.

        A `TxAbort` exception rolls back and returns `exc.value`.
        """
        raise NotImplementedError

    def snapshot(self, to_dir: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _prefix_end(prefix: bytes) -> bytes | None:
    p = bytearray(prefix)
    while p:
        if p[-1] != 0xFF:
            p[-1] += 1
            return bytes(p)
        p.pop()
    return None


from .open import open_db  # noqa: E402

__all__ = ["Db", "Tree", "Tx", "TxAbort", "open_db"]
