"""Rebalance observatory: layout-transition flight deck + federated
cluster event timeline.

Garage's defining claim is that layout changes need no consensus: the
`LayoutHistory` CRDT (rpc/layout/history.py) converges by gossip while
reads and writes keep flowing against every active version.  This module
is the narration layer for that window.  While layout versions diverge,
a `TransitionTracker` on every node tracks per-partition migration state
(pending / moving / synced), bytes moved attributed to (source → dest)
node pairs, a rebalance-throughput EWMA with an ETA, and the CRDT
convergence lag — and each node gossips its ack'd/synced layout version
in the telemetry digest (`lt.*` keys), so ANY node can report the
cluster's version spread and per-node staleness.  On completion the
tracker emits a structured `transition-report` flight event: the
artifact the grow/drain chaos campaign gates on.

The federated event timeline rides the same plane: every node banks
`flight.record_event` events locally (utils/flight.py); the admin
fan-out here merges each node's recent events into one causally-ordered
timeline by correcting per-node wall clocks with the NTP-style offsets
the status exchange estimates (rpc/system.py).  Ordering is only as
good as those offsets — which is why `cluster_node_clock_skew_ms` is a
first-class federated family with a `SKEW!` flag in `cluster top`.
"""

from __future__ import annotations

import asyncio
import logging
import statistics
import time

from ..utils.data import hex_of
from ..utils.metrics import registry as default_registry

logger = logging.getLogger("garage.transition")

# EWMA smoothing for the per-peer clock offset (rpc/system.py feeds one
# sample per status exchange, i.e. every ~10 s: heavy smoothing would
# take minutes to converge after a step change)
OFFSET_ALPHA = 0.3
# EWMA smoothing for rebalance throughput / sync-fraction rate
RATE_ALPHA = 0.3
# retained sync-fraction samples per transition (the report decimates
# further; the cap bounds a week-long stalled transition's memory)
CURVE_MAX = 256
# sync-fraction samples are taken at most this often (digest collection
# and admin polling both drive _sample; they must not double-count rate)
SAMPLE_MIN_INTERVAL = 1.0
# flight events retained per node for the federated timeline
EVENTS_MAX = 256

SEVERITIES = ("info", "warn", "critical")


def severity_rank(sev) -> int:
    """info=0 < warn=1 < critical=2; unknown strings rank as info."""
    try:
        return SEVERITIES.index(sev)
    except ValueError:
        return 0


def estimate_offset(t0: float, t_peer: float, t3: float) -> tuple[float, float]:
    """One-exchange NTP-style clock offset estimate.

    `t0`/`t3` are the local wall clock just before/after the RPC;
    `t_peer` is the peer's wall clock while handling it.  Assuming the
    network path is symmetric the peer stamped its clock at the local
    midpoint, so `offset = t_peer - (t0 + t3) / 2` (positive = the
    peer's clock runs AHEAD of ours).  Returns (offset, rtt) in
    seconds; the asymmetry error is bounded by rtt/2, which is why
    callers EWMA across exchanges instead of trusting one sample.
    """
    rtt = max(0.0, t3 - t0)
    return t_peer - (t0 + t3) / 2.0, rtt


def merge_timeline(per_node) -> list[dict]:
    """Merge per-node event lists into one skew-corrected timeline.

    `per_node` is a list of `(node_hex16, offset_secs, events)` where
    `offset_secs` is the querying node's estimate of that peer's clock
    offset (None for self/unknown → 0).  Each event's wall-clock
    `start` is mapped onto the querying node's clock
    (`t_local = t_peer - offset`), then the union is sorted by the
    corrected time.  Causal order is only guaranteed down to the
    residual skew — which the output carries per-event (`skewMs`) so a
    reader can see how much to trust a close ordering.
    """
    out = []
    for node, offset, events in per_node:
        off = float(offset or 0.0)
        for ev in events:
            try:
                start = float(ev.get("start"))
            except (TypeError, ValueError):
                continue
            out.append(
                {
                    "node": node,
                    "time": start - off,
                    "rawTime": start,
                    "skewMs": round(off * 1000.0, 3),
                    "name": ev.get("name"),
                    "severity": ev.get("severity", "info"),
                    "attrs": ev.get("attrs") or {},
                }
            )
    out.sort(key=lambda e: (e["time"], e["node"], e["name"] or ""))
    return out


def local_events(recorder, since: float = 0.0, min_severity: str = "info",
                 limit: int = EVENTS_MAX) -> list[dict]:
    """This node's banked flight events strictly newer than `since`
    (the node's OWN wall clock — callers skew-correct afterwards),
    at or above `min_severity`.  The event bank is the recorder's
    dedicated `events` ring, not the slow-request ring: a burst of slow
    requests must not evict the durability alert an operator is
    grepping for."""
    if recorder is None:
        return []
    floor = severity_rank(min_severity)
    evs = []
    for rec in list(getattr(recorder, "events", ())):
        if rec.get("start", 0.0) <= since:
            continue
        if severity_rank(rec.get("severity", "info")) < floor:
            continue
        evs.append(
            {
                "name": rec.get("name"),
                "start": rec.get("start"),
                "severity": rec.get("severity", "info"),
                "attrs": rec.get("attrs") or {},
            }
        )
    return evs[-limit:]


def _decimate(curve: list, keep: int = 64) -> list:
    """Thin a sync-fraction curve for the transition report (keep the
    endpoints; stride the middle)."""
    if len(curve) <= keep:
        return [list(p) for p in curve]
    step = (len(curve) - 1) / (keep - 1)
    idx = sorted({round(i * step) for i in range(keep)} | {len(curve) - 1})
    return [list(curve[i]) for i in idx]


class TransitionTracker:
    """Narrates one layout transition end to end on this node.

    Subscribes to the LayoutManager so it sees every CRDT merge: a
    transition OPENS when a second version with a ring assignment
    appears, and CLOSES when trim() retires the old one (back to a
    single active version) — at which point a `transition-report`
    flight event is emitted and kept as `last_report`.  While open,
    the block plane attributes every migrated byte to a (src → dst)
    pair via `note_transfer`, and `_sample()` (driven by digest
    collection / admin polling, rate-limited) maintains the
    sync-fraction curve, the throughput EWMA and the ETA.
    """

    def __init__(self, garage, registry=None):
        self.garage = garage
        self.registry = registry if registry is not None else default_registry
        self.clock = time.monotonic
        self.active = False
        self.from_version: int | None = None
        self.target_version: int | None = None
        self._open_mono: float | None = None
        self._open_wall: float | None = None
        # (src_hex16, dst_hex16) -> bytes moved during this transition
        self.pair_bytes: dict[tuple[str, str], int] = {}
        self.bytes_total = 0
        # partitions some migrated byte was attributed to ("moving")
        self.partitions_touched: set[int] = set()
        self.curve: list[tuple[float, float]] = []  # (elapsed_s, frac)
        self._thr_ewma: float | None = None  # bytes/s
        self._frac_rate: float | None = None  # sync fraction / s
        self._last_sample: tuple[float, float, int] | None = None
        self._max_burn = 0.0
        self._canary_failed = False
        self.last_report: dict | None = None
        self.reports = 0
        garage.layout_manager.subscribe(self._on_layout_change)
        self._on_layout_change()

    # --- layout-change state machine -----------------------------------------

    def _active_versions(self) -> int:
        h = self.garage.layout_manager.history
        return sum(1 for v in h.versions if v.ring_assignment)

    def _on_layout_change(self) -> None:
        # MUST stay cheap and synchronous: LayoutManager._notify runs on
        # the event loop for every CRDT delta during a transition.
        h = self.garage.layout_manager.history
        n_active = self._active_versions()
        if n_active >= 2 and not self.active:
            self._open(h)
        elif self.active and n_active <= 1:
            self._close()
        elif self.active:
            # a second apply landed mid-transition: retarget, keep the
            # accounting (the report spans the whole divergence window)
            self.target_version = h.current().version

    def _open(self, h) -> None:
        self.active = True
        active = [v for v in h.versions if v.ring_assignment]
        self.from_version = active[0].version
        self.target_version = h.current().version
        self._open_mono = self.clock()
        self._open_wall = time.time()
        self.pair_bytes = {}
        self.bytes_total = 0
        self.partitions_touched = set()
        self.curve = []
        self._thr_ewma = None
        self._frac_rate = None
        self._last_sample = None
        self._max_burn = 0.0
        self._canary_failed = False
        logger.info(
            "layout transition opened: v%s -> v%s",
            self.from_version, self.target_version,
        )

    def _close(self) -> None:
        from ..utils import flight

        self._sample(force=True)
        duration = self.clock() - (self._open_mono or self.clock())
        pairs = [
            {"src": s, "dst": d, "bytes": b}
            for (s, d), b in sorted(
                self.pair_bytes.items(), key=lambda kv: -kv[1]
            )
        ]
        report = {
            "version": self.target_version,
            "fromVersion": self.from_version,
            "openedAt": self._open_wall,
            "durationSecs": round(duration, 3),
            "bytesMoved": self.bytes_total,
            "pairs": pairs,
            "partitionsTouched": len(self.partitions_touched),
            "syncCurve": _decimate(self.curve),
            "sloBurnMax": round(self._max_burn, 3),
            "canaryOk": not self._canary_failed,
        }
        self.last_report = report
        self.reports += 1
        self.active = False
        severity = "warn" if (self._canary_failed or self._max_burn > 1.0) \
            else "info"
        import json as _json

        attrs = {
            k: (_json.dumps(v) if isinstance(v, (list, dict)) else v)
            for k, v in report.items()
        }
        try:
            flight.record_event("transition-report", attrs, severity=severity)
        # graft-lint: allow-swallow(the report is kept as last_report either way)
        except Exception:  # noqa: BLE001 — narration must not break layout
            logger.exception("transition-report event emission failed")
        logger.info(
            "layout transition closed: v%s in %.1fs, %d bytes moved",
            self.target_version, duration, self.bytes_total,
        )

    # --- byte attribution (block plane hooks) --------------------------------

    def note_transfer(self, src: bytes, dst: bytes, nbytes: int,
                      partition: int | None = None) -> None:
        """Attribute `nbytes` migrated from `src` to `dst`.  No-op
        outside a transition: steady-state fetches (reads, repair) are
        not rebalance traffic."""
        if not self.active or nbytes <= 0:
            return
        key = (hex_of(src)[:16], hex_of(dst)[:16])
        self.pair_bytes[key] = self.pair_bytes.get(key, 0) + int(nbytes)
        self.bytes_total += int(nbytes)
        if partition is not None:
            self.partitions_touched.add(int(partition))
        self.registry.incr(
            "layout_transition_pair_bytes_total",
            (("src", key[0]), ("dst", key[1])),
            by=int(nbytes),
        )

    # --- sampling ------------------------------------------------------------

    def sync_fraction(self) -> float:
        from ..block.durability import layout_transition

        return float(
            layout_transition(self.garage.layout_manager.history)["progress"]
        )

    def partition_states(self) -> dict:
        """Per-partition migration state counts under the newest
        version: `synced` (every assigned node's sync tracker covers
        it), `moving` (not synced, but bytes were attributed to it),
        `pending` (the rest)."""
        h = self.garage.layout_manager.history
        cur = h.current()
        if not cur.ring_assignment:
            return {"total": 0, "synced": 0, "moving": 0, "pending": 0}
        total = len(cur.ring_assignment)
        synced = moving = 0
        for p in range(total):
            nodes = cur.nodes_of_partition(p)
            if nodes and all(h.sync.get(n) >= cur.version for n in nodes):
                synced += 1
            elif p in self.partitions_touched:
                moving += 1
        return {
            "total": total,
            "synced": synced,
            "moving": moving,
            "pending": total - synced - moving,
        }

    def _sample(self, force: bool = False) -> None:
        if not self.active:
            return
        now = self.clock()
        if (
            not force
            and self._last_sample is not None
            and now - self._last_sample[0] < SAMPLE_MIN_INTERVAL
        ):
            return
        frac = self.sync_fraction()
        elapsed = now - (self._open_mono or now)
        if self._last_sample is not None:
            dt = now - self._last_sample[0]
            if dt > 0:
                thr = (self.bytes_total - self._last_sample[2]) / dt
                self._thr_ewma = (
                    thr if self._thr_ewma is None
                    else RATE_ALPHA * thr + (1 - RATE_ALPHA) * self._thr_ewma
                )
                fr = (frac - self._last_sample[1]) / dt
                if fr > 0:
                    self._frac_rate = (
                        fr if self._frac_rate is None
                        else RATE_ALPHA * fr
                        + (1 - RATE_ALPHA) * self._frac_rate
                    )
        self._last_sample = (now, frac, self.bytes_total)
        if len(self.curve) < CURVE_MAX and (
            not self.curve or frac != self.curve[-1][1] or force
        ):
            self.curve.append((round(elapsed, 2), frac))
        self._sample_slo()

    def _sample_slo(self) -> None:
        """SLO burn + canary verdicts DURING the window: 'did the
        rebalance hurt clients' is the question the report answers."""
        g = self.garage
        slo = getattr(g, "slo_tracker", None)
        if slo is not None:
            try:
                c = slo.compute()
                burn = max(
                    (float(o.get("burn_rate", 0.0)) for o in c.values()),
                    default=0.0,
                )
                self._max_burn = max(self._max_burn, burn)
            # graft-lint: allow-swallow(SLO sampling is an optional report enrichment)
            except Exception:  # noqa: BLE001
                logger.debug("slo sampling during transition failed",
                             exc_info=True)
        canary = getattr(g, "canary", None)
        if canary is not None and getattr(canary, "healthy", None) == 0.0:
            self._canary_failed = True

    # --- derived views -------------------------------------------------------

    def eta_secs(self) -> float | None:
        """Seconds until sync fraction 1.0 at the EWMA'd rate; None
        when idle or the rate hasn't established."""
        if not self.active or not self._frac_rate or self._last_sample is None:
            return None
        remaining = max(0.0, 1.0 - self._last_sample[1])
        if remaining == 0.0:
            return 0.0
        return round(remaining / self._frac_rate, 1)

    def clock_skew_secs(self) -> float | None:
        """This node's wall-clock skew vs the cluster: the median of
        the per-peer offsets the status exchange estimated (median, not
        mean — one peer with a broken clock must not smear everyone's
        skew estimate).  Positive = peers run ahead of us."""
        offs = [
            o["offset"]
            for o in getattr(self.garage.system, "clock_offsets", {}).values()
        ]
        if not offs:
            return None
        return statistics.median(offs)

    def digest_fields(self) -> dict:
        """The `lt` telemetry-digest section (gossiped to every peer in
        NodeStatus).  Keys are additive under DIGEST_VERSION 1; peers
        treat unknown/missing keys as absent."""
        g = self.garage
        h = g.layout_manager.history
        me = g.system.id
        self._sample()
        d = {
            "v": h.current().version,
            "ack": h.ack.get(me),
            "sync": h.sync.get(me),
            "act": self._active_versions(),
            "frac": round(self.sync_fraction(), 4),
            "rep": self.reports,
        }
        sk = self.clock_skew_secs()
        if sk is not None:
            d["sk"] = round(sk * 1000.0, 3)
        if self.active:
            d["mvb"] = self.bytes_total
            d["els"] = round(self.clock() - (self._open_mono or 0.0), 1)
            if self._thr_ewma is not None:
                d["thr"] = round(self._thr_ewma, 1)
            eta = self.eta_secs()
            if eta is not None:
                d["eta"] = eta
        return d

    def snapshot(self) -> dict:
        """This node's full local view (one shape for admin HTTP, admin
        RPC and the CLI — the one-serialization rule)."""
        self._sample()
        h = self.garage.layout_manager.history
        sk = self.clock_skew_secs()
        offsets = {}
        now = self.clock()
        for pid, o in getattr(
            self.garage.system, "clock_offsets", {}
        ).items():
            offsets[hex_of(pid)[:16]] = {
                "offsetMs": round(o["offset"] * 1000.0, 3),
                "rttMs": round(o["rtt"] * 1000.0, 3),
                "ageSecs": round(now - o["at"], 1),
            }
        return {
            "inTransition": self.active,
            "version": h.current().version,
            "fromVersion": self.from_version if self.active else None,
            "activeVersions": self._active_versions(),
            "syncFraction": round(self.sync_fraction(), 4),
            "partitions": self.partition_states(),
            "bytesMoved": self.bytes_total if self.active else 0,
            "pairs": [
                {"src": s, "dst": d, "bytes": b}
                for (s, d), b in sorted(
                    self.pair_bytes.items(), key=lambda kv: -kv[1]
                )
            ] if self.active else [],
            "throughputBytesPerSec": (
                round(self._thr_ewma, 1)
                if self.active and self._thr_ewma is not None
                else None
            ),
            "etaSecs": self.eta_secs(),
            "elapsedSecs": (
                round(self.clock() - (self._open_mono or 0.0), 1)
                if self.active
                else None
            ),
            "syncCurve": [list(p) for p in self.curve],
            "maxSloBurn": round(self._max_burn, 3),
            "canaryOk": not self._canary_failed,
            "lastReport": self.last_report,
            "clockSkewMs": round(sk * 1000.0, 3) if sk is not None else None,
            "clockOffsets": offsets,
        }


# --- federated responses (one serialization for HTTP/RPC/CLI) ----------------


def transition_response(garage) -> dict:
    """Local transition detail + every node's gossiped `lt` digest +
    the cluster aggregate (version spread, stale nodes, worst skew)."""
    from .telemetry_digest import _dig, _node_rows

    tt = getattr(garage, "transition_tracker", None)
    rows = _node_rows(garage.system)
    nodes = []
    acks, versions = [], []
    skew_worst = None
    for r in rows:
        lt = _dig(r, "lt")
        lt = lt if isinstance(lt, dict) else None
        nodes.append(
            {
                "id": r["id"],
                "isUp": r["isUp"],
                "isSelf": r.get("isSelf", False),
                "lt": lt,
            }
        )
        if lt:
            if isinstance(lt.get("ack"), (int, float)):
                acks.append(int(lt["ack"]))
            if isinstance(lt.get("v"), (int, float)):
                versions.append(int(lt["v"]))
            sk = lt.get("sk")
            if isinstance(sk, (int, float)) and (
                skew_worst is None or abs(sk) > abs(skew_worst)
            ):
                skew_worst = sk
    newest = max(versions) if versions else None
    spread = (newest - min(acks)) if versions and acks else 0
    stale = sorted(
        n["id"]
        for n in nodes
        if n["lt"]
        and newest is not None
        and isinstance(n["lt"].get("ack"), (int, float))
        and int(n["lt"]["ack"]) < newest
    )
    return {
        "node": hex_of(garage.system.id),
        "enabled": tt is not None,
        "local": tt.snapshot() if tt is not None else None,
        "cluster": {
            "nodes": nodes,
            "aggregate": {
                "newestVersion": newest,
                "versionSpread": spread,
                "staleNodes": stale,
                "clockSkewWorstMs": skew_worst,
                "clockSkewWarnMs": garage.config.admin.clock_skew_warn_msec,
                "nodesReporting": sum(1 for n in nodes if n["lt"]),
            },
        },
    }


async def cluster_events_response(
    garage, since: float = 0.0, min_severity: str = "info",
    timeout: float = 5.0,
) -> dict:
    """Fan out to every connected peer's event bank and merge the union
    with the local bank into one skew-corrected timeline.  A peer that
    fails/times out is reported in `nodesFailed`, never awaited past
    `timeout` — the timeline degrades to fewer nodes, not to an error."""
    sysd = garage.system
    me = hex_of(sysd.id)[:16]
    per_node = [
        (
            me,
            0.0,
            local_events(
                getattr(garage, "flight_recorder", None), since, min_severity
            ),
        )
    ]
    responded, failed = [me], []

    async def ask(pid):
        resp = await sysd.events_ep.call(
            pid,
            {"since": since, "sev": min_severity},
            timeout=timeout,
        )
        return resp.body

    peers = list(sysd.peering.connected_peers())
    results = await asyncio.gather(
        *[ask(pid) for pid in peers], return_exceptions=True
    )
    for pid, res in zip(peers, results):
        hexid = hex_of(pid)[:16]
        if isinstance(res, BaseException):
            logger.debug("event fan-out to %s failed: %r", hexid, res)
            failed.append(hexid)
            continue
        off = sysd.clock_offsets.get(pid, {}).get("offset", 0.0)
        per_node.append((hexid, off, res if isinstance(res, list) else []))
        responded.append(hexid)
    return {
        "node": hex_of(sysd.id),
        "since": since,
        "minSeverity": min_severity,
        "nodesResponding": sorted(responded),
        "nodesFailed": sorted(failed),
        "events": merge_timeline(per_node),
    }
