"""Per-peer health tracking + circuit breaker for the RPC layer.

The quorum machinery (rpc_helper.py) used to treat every peer as equally
healthy: a crashed node cost the full default timeout (up to 30 s) on
every call that touched it.  This module gives the RPC layer a memory:

  - per-peer EWMA of call success (1.0 = all succeeding) and of observed
    RTT, fed from every RpcHelper call outcome and from peering pings;
  - a circuit breaker per peer: CLOSED (normal) -> OPEN after
    `open_after` consecutive transport failures (calls fast-fail instead
    of burning a timeout) -> HALF_OPEN after `open_cooldown` seconds
    (a single probe call is let through) -> CLOSED on probe success,
    back to OPEN on probe failure;
  - adaptive per-peer timeouts derived from the RTT EWMA, so a call to a
    historically-1 ms peer fails in ~1 s, not 30.

Only TRANSPORT failures (timeout, connection loss, unreachable) feed the
breaker: a peer that answers with an application error (RemoteError) is
alive and counts as a transport success.

Observability: state transitions and fast-fails are counted in
utils/metrics (`rpc_breaker_transition_counter{peer,to}`,
`rpc_breaker_fastfail_counter{peer}`), the current state is exported as a
gauge (`rpc_peer_breaker_state{peer}`: 0=closed 1=half-open 2=open), and
`snapshot()` feeds the admin status endpoint.

Reference analog: none in the reference for the breaker itself (garage
relies on short rpc timeouts); the health-aware ordering extends
rpc_helper.rs:621's rtt ordering with liveness.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..utils.error import Error
from ..utils.metrics import registry

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class PeerUnavailable(Error):
    """Fast-fail: the peer's circuit breaker is open."""


@dataclass
class _Peer:
    state: str = CLOSED
    consecutive_failures: int = 0
    success_ewma: float = 1.0
    rtt_ewma: float | None = None
    opened_at: float = 0.0
    probe_inflight: bool = False
    transitions: int = 0
    successes: int = 0
    failures: int = 0
    # EC read attribution (rpc/traffic.py): per-peer piece_fetch
    # latency/bytes EWMAs feeding the slow-rank ranking item 1a's
    # hedged reads will key off.  Separate from rtt_ewma on purpose:
    # that one blends every RPC (pings, table ops); a slow DISK on a
    # peer shows up here and nowhere else.
    piece_fetches: int = 0
    piece_bytes: float = 0.0
    piece_lat_ewma: float | None = None
    piece_bytes_ewma: float | None = None


class PeerHealth:
    """Tracker + breaker state for all peers, from one node's viewpoint."""

    # breaker tuning (per-instance overridable; tests use small values).
    # The cooldown bounds how long a HEALED peer keeps being fast-failed:
    # after a real outage ends, nothing but a probe (or a background
    # ping) can close the breaker, and every fast-failed sync/queue
    # worker meanwhile sinks deeper into its own error backoff — a long
    # cooldown therefore extends the effective outage well past heal.
    # 5 s (the classic Hystrix default) keeps that extension small.
    open_after = 5  # consecutive transport failures before opening
    open_cooldown = 5.0  # seconds OPEN before letting a probe through
    ewma_alpha = 0.2  # weight of the newest sample
    sick_threshold = 0.5  # success EWMA below this = sick (ordering)

    # adaptive timeout: clamp(rtt_ewma * mult + slack, floor, default)
    timeout_rtt_mult = 8.0
    timeout_slack = 0.5  # seconds, covers handler work
    timeout_floor = 1.0  # never time out faster than this

    def __init__(self, our_id: bytes, clock=time.monotonic):
        self.our_id = our_id
        self.clock = clock
        self.peers: dict[bytes, _Peer] = {}

    def _peer(self, node: bytes) -> _Peer:
        p = self.peers.get(node)
        if p is None:
            p = self.peers[node] = _Peer()
        return p

    def _lbl(self, node: bytes) -> tuple:
        return (("peer", node.hex()[:16]),)

    def _transition(self, node: bytes, p: _Peer, to: str) -> None:
        if p.state == to:
            return
        p.state = to
        p.transitions += 1
        registry.incr(
            "rpc_breaker_transition_counter", self._lbl(node) + (("to", to),)
        )
        registry.set_gauge(
            "rpc_peer_breaker_state", self._lbl(node), _STATE_GAUGE[to]
        )

    # --- call gating ---------------------------------------------------------

    def acquire(self, node: bytes) -> bool:
        """Gate a call to `node`.  Raises PeerUnavailable (fast-fail) when
        the breaker is open; in half-open, admits a single probe and
        fast-fails the rest.  Returns True when THIS call claimed the
        half-open probe slot — only such calls may release() it."""
        if node == self.our_id:
            return False
        p = self._peer(node)
        if p.state == OPEN:
            if self.clock() - p.opened_at >= self.open_cooldown:
                self._transition(node, p, HALF_OPEN)
            else:
                registry.incr("rpc_breaker_fastfail_counter", self._lbl(node))
                raise PeerUnavailable(
                    f"peer {node.hex()[:16]} circuit open "
                    f"({p.consecutive_failures} consecutive failures)"
                )
        if p.state == HALF_OPEN:
            if p.probe_inflight:
                registry.incr("rpc_breaker_fastfail_counter", self._lbl(node))
                raise PeerUnavailable(
                    f"peer {node.hex()[:16]} half-open probe already in flight"
                )
            p.probe_inflight = True
            return True
        return False

    def release(self, node: bytes) -> None:
        """The probe call that CLAIMED the half-open slot (acquire
        returned True) ended without a success/failure verdict (e.g. it
        was cancelled): free the slot so the next probe can run.  Callers
        whose acquire returned False must not call this — they would free
        a slot someone else holds."""
        p = self.peers.get(node)
        if p is not None:
            p.probe_inflight = False

    # --- outcome feed --------------------------------------------------------

    def record_success(
        self, node: bytes, rtt: float | None = None, probe: bool = False
    ) -> None:
        """`probe`: this verdict comes from the call that claimed the
        half-open probe slot (acquire returned True)."""
        if node == self.our_id:
            return
        p = self._peer(node)
        p.consecutive_failures = 0
        p.successes += 1
        a = self.ewma_alpha
        p.success_ewma = (1 - a) * p.success_ewma + a
        if rtt is not None:
            p.rtt_ewma = (
                rtt if p.rtt_ewma is None else (1 - a) * p.rtt_ewma + a * rtt
            )
        if p.state != CLOSED:
            # half-open probe succeeded, or late evidence of life while
            # open (e.g. a peering ping, which bypasses the breaker)
            self._transition(node, p, CLOSED)
            p.probe_inflight = False  # any probe slot is void once closed
        elif probe:
            p.probe_inflight = False

    def record_failure(
        self,
        node: bytes,
        timed_out_after: float | None = None,
        probe: bool = False,
    ) -> None:
        """`timed_out_after`: set when the failure was a TIMEOUT after
        that many seconds — widens the peer's adaptive-timeout window
        TCP-RTO-style (a timeout says the true response time is above
        the window we allowed; double it for the next try; successes
        shrink it back through the EWMA).  Without this, a load spike
        that pushes responses past the adaptive window is metastable:
        every call times out, the window never re-learns, the breaker
        flaps open forever.

        `probe`: this verdict comes from the call that claimed the
        half-open probe slot.  In HALF_OPEN only the probe's own failure
        re-opens (and frees the slot) — stale verdicts from calls that
        started before the outage, or a concurrently-failing ping, must
        not hijack a probe still in flight."""
        if node == self.our_id:
            return
        p = self._peer(node)
        if timed_out_after is not None:
            widened = 2.0 * timed_out_after / self.timeout_rtt_mult
            p.rtt_ewma = max(p.rtt_ewma or 0.0, widened)
        p.consecutive_failures += 1
        p.failures += 1
        p.success_ewma = (1 - self.ewma_alpha) * p.success_ewma
        if p.state == HALF_OPEN:
            if probe:
                p.probe_inflight = False
                p.opened_at = self.clock()
                self._transition(node, p, OPEN)
        elif p.state == CLOSED and p.consecutive_failures >= self.open_after:
            p.opened_at = self.clock()
            self._transition(node, p, OPEN)

    def record_piece_fetch(
        self, node: bytes, secs: float, nbytes: int
    ) -> None:
        """One successful remote EC piece fetch from `node` (fed by
        block/manager.py `_fetch_piece`).  Failures don't land here —
        they feed the breaker via record_failure; the ranking flags
        sick/open peers ahead of any latency number anyway."""
        if node == self.our_id:
            return
        p = self._peer(node)
        a = self.ewma_alpha
        p.piece_fetches += 1
        p.piece_bytes += nbytes
        p.piece_lat_ewma = (
            secs
            if p.piece_lat_ewma is None
            else (1 - a) * p.piece_lat_ewma + a * secs
        )
        p.piece_bytes_ewma = (
            float(nbytes)
            if p.piece_bytes_ewma is None
            else (1 - a) * p.piece_bytes_ewma + a * nbytes
        )

    def fetch_latency_estimate(self, node: bytes) -> float | None:
        """Best available latency estimate (seconds) for one remote
        piece/block fetch from `node`: the piece-fetch EWMA when this
        node has fetched from it before (it folds in the peer's DISK,
        not just its transport), else the all-RPC rtt EWMA, else None.
        The hedged-read delay (block/manager.py) seeds from this."""
        p = self.peers.get(node)
        if p is None:
            return None
        if p.piece_lat_ewma is not None:
            return p.piece_lat_ewma
        return p.rtt_ewma

    def piece_fetch_ranking(self) -> list[dict]:
        """Slowest-first per-peer read attribution: sick / breaker-open
        peers rank ahead of everything (they are the slowest a read can
        get), then by piece-fetch latency EWMA descending.  Peers with
        neither signal are omitted."""
        rows = []
        for node, p in self.peers.items():
            sick = self.is_sick(node)
            if p.piece_fetches == 0 and not sick:
                continue
            rows.append(
                {
                    "peer": node.hex(),
                    "state": p.state,
                    "sick": sick,
                    "pieceFetches": p.piece_fetches,
                    "pieceBytes": int(p.piece_bytes),
                    "latMsecEwma": (
                        round(p.piece_lat_ewma * 1000, 3)
                        if p.piece_lat_ewma is not None
                        else None
                    ),
                    "bytesEwma": (
                        round(p.piece_bytes_ewma, 1)
                        if p.piece_bytes_ewma is not None
                        else None
                    ),
                    "successEwma": round(p.success_ewma, 4),
                }
            )
        rows.sort(
            key=lambda r: (
                0 if r["sick"] else 1,
                -(r["latMsecEwma"] or 0.0),
                r["peer"],
            )
        )
        return rows

    # --- consumers -----------------------------------------------------------

    def state_of(self, node: bytes) -> str:
        p = self.peers.get(node)
        return p.state if p else CLOSED

    def is_sick(self, node: bytes) -> bool:
        """Known-bad peers to deprioritize in read ordering: breaker not
        closed, or success rate collapsed."""
        p = self.peers.get(node)
        if p is None:
            return False
        return p.state != CLOSED or p.success_ewma < self.sick_threshold

    def rtt_of(self, node: bytes) -> float | None:
        p = self.peers.get(node)
        return p.rtt_ewma if p else None

    def adaptive_timeout(self, node: bytes, default: float) -> float:
        """Per-peer call timeout from the RTT EWMA, clamped to
        [timeout_floor, default].  Without RTT history: the default."""
        p = self.peers.get(node)
        if p is None or p.rtt_ewma is None:
            return default
        t = p.rtt_ewma * self.timeout_rtt_mult + self.timeout_slack
        return min(default, max(self.timeout_floor, t))

    def snapshot(self) -> dict[str, dict]:
        """Per-peer health for the admin status endpoint."""
        out: dict[str, dict] = {}
        for node, p in self.peers.items():
            out[node.hex()] = {
                "state": p.state,
                "successEwma": round(p.success_ewma, 4),
                "rttMsecEwma": (
                    round(p.rtt_ewma * 1000, 3) if p.rtt_ewma is not None else None
                ),
                "consecutiveFailures": p.consecutive_failures,
                "successes": p.successes,
                "failures": p.failures,
                "transitions": p.transitions,
            }
        return out
