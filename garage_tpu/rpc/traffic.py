"""Traffic heat observatory: streaming hot-object analytics, per-peer
read attribution, and replayable workload profiles.

The latency X-ray (PR 6) says which *phase* of a request was slow and
the cluster digest (PR 5) says which *node* is sick — but nothing could
say which *object* is hot, how skewed the keyspace is, what the
read/write mix looks like, or which peer is the slow rank on an EC GET.
ROADMAP item 1's hot-object cache and hedged systematic reads, and
item 5's workload generator, all need exactly those numbers first.

  - `TrafficObservatory` — a process-wide singleton (PhaseAggregator
    discipline: several in-process test nodes share one registry and
    one S3 frontend path, so per-node instances would double-count)
    fed by the S3 request path with (op, bucket, key, bytes, latency).
    Bounded memory by construction: Space-Saving top-K over object
    keys and buckets, a Count-Min sketch over the full keyspace, a
    log2 object-size histogram, per-op counters and streaming
    inter-arrival moments (utils/sketch.py).  NO per-key metrics
    families — hot-key data is served from the JSON endpoints only
    (the metrics-lint cardinality guard enforces this).

  - per-peer `piece_fetch` attribution rides PR 1's peer-health
    structures (rpc/peer_health.py `record_piece_fetch`): latency /
    bytes EWMAs per peer from the EC read path, surfacing the
    "slow rank" ranking item 1a's hedged reads will key off.

  - surfaces: admin `GET /v1/traffic` (top-K, mix, skew, slow peers,
    cluster rollup from the gossiped `trf.*` digest keys),
    `GET /v1/traffic/profile` (a REPLAYABLE workload profile: op mix,
    size distribution, popularity skew, inter-arrival stats — the
    contract item 5's generator consumes), admin-RPC + `cli cluster
    hot`, federated `cluster_node_traffic_*` families on
    `/metrics/cluster`, and a `hot` column in `cluster top`.
"""

from __future__ import annotations

import logging
import math
import time

from ..utils.sketch import CountMin, SpaceSaving, zipf_exponent

logger = logging.getLogger("garage.traffic")

# operation classes tracked by the observatory — CLOSED like the latency
# phase catalogue so the op-mix surface stays bounded
OP_KINDS = ("get", "put", "head", "delete", "list", "other")
READ_OPS = frozenset({"get", "head"})
WRITE_OPS = frozenset({"put", "delete"})

# object-size histogram bounds: pow2 bytes, 1 B .. 1 GiB (+overflow)
SIZE_BOUNDS = [2 ** i for i in range(31)]

_LN2 = math.log(2.0)


def classify_op(method: str, key: str, query) -> str:
    """S3 request -> op class.  `query` is the request's query mapping
    (only key membership is read)."""
    if method == "GET":
        return "get" if key else "list"
    if method == "HEAD":
        return "head"
    if method == "PUT":
        return "put"
    if method == "DELETE":
        return "delete"
    if method == "POST":
        if "delete" in query:
            return "delete"  # DeleteObjects
        if "uploads" in query or "uploadId" in query:
            # multipart initiate/complete: control-plane — the body is
            # an XML manifest, not object payload; counting it as a
            # "put" would inject ~1 KiB samples into the size histogram
            # the workload generator replays (the data moved through
            # the part PUTs, already recorded)
            return "other"
        return "put"  # PostObject browser form upload
    return "other"


class TrafficObservatory:
    """Streaming per-process S3 traffic summary.  All updates are O(1)
    dict/sketch arithmetic (lazy decay sweeps are O(capacity), at most
    ~16 per halflife) — safe on the request path, no numpy, no I/O."""

    def __init__(
        self,
        topk: int = 256,
        halflife: float | None = 600.0,
        clock=time.monotonic,
    ):
        self.topk = int(topk)
        self.halflife = halflife
        self.clock = clock
        self.enabled = False
        self._reset_state()

    def _reset_state(self) -> None:
        hl, clock = self.halflife, self.clock
        self.keys = SpaceSaving(self.topk, halflife=hl, clock=clock)
        self.buckets = SpaceSaving(
            max(16, self.topk // 4), halflife=hl, clock=clock
        )
        self.key_freq = CountMin(width=2048, depth=4, halflife=hl, clock=clock)
        self.ops: dict[str, int] = dict.fromkeys(OP_KINDS, 0)
        self.bytes_moved = 0
        # op -> [count, sum_secs, max_secs]
        self.latency: dict[str, list[float]] = {
            op: [0, 0.0, 0.0] for op in OP_KINDS
        }
        self.size_counts = [0] * (len(SIZE_BOUNDS) + 1)
        # streaming inter-arrival moments: n, sum dt, sum dt^2
        self._last_arrival: float | None = None
        self._ia = [0, 0.0, 0.0]
        self.started_at = clock()

    def reset(self) -> None:
        """Drop all accumulated state (test/bench isolation — the
        singleton outlives any one in-process node)."""
        self._reset_state()

    def reconfigure(self, topk: int, halflife: float | None) -> None:
        """Apply sizing knobs; resets state only when they changed (the
        sketches' geometry is baked into their arrays)."""
        if (int(topk), halflife) == (self.topk, self.halflife):
            return
        self.topk = int(topk)
        self.halflife = halflife
        self._reset_state()

    # --- the S3 request-path hook --------------------------------------------

    def record_http(
        self, method: str, bucket: str, key: str, query,
        nbytes: int, secs: float,
    ) -> None:
        """One admitted S3 request (shed 503s are not traffic — the
        overload plane's invariant).  Must never raise: it runs in the
        request handler's finally."""
        if not self.enabled:
            return
        op = classify_op(method, key, query)
        self.ops[op] += 1
        lat = self.latency[op]
        lat[0] += 1
        lat[1] += secs
        if secs > lat[2]:
            lat[2] = secs
        now = self.clock()
        if self._last_arrival is not None:
            dt = max(0.0, now - self._last_arrival)
            self._ia[0] += 1
            self._ia[1] += dt
            self._ia[2] += dt * dt
        self._last_arrival = now
        if bucket:
            self.buckets.incr(bucket)
            if key:
                composite = f"{bucket}/{key}"
                self.keys.incr(composite)
                self.key_freq.incr(composite)
        if nbytes and op in ("get", "put"):
            self.bytes_moved += nbytes
            i = min(
                max(0, (int(nbytes) - 1).bit_length()), len(SIZE_BOUNDS)
            )
            self.size_counts[i] += 1

    # --- derived numbers ------------------------------------------------------

    @property
    def total_ops(self) -> int:
        return sum(self.ops.values())

    def read_fraction(self) -> float | None:
        reads = sum(self.ops[o] for o in READ_OPS)
        writes = sum(self.ops[o] for o in WRITE_OPS)
        return (
            round(reads / (reads + writes), 4) if reads + writes else None
        )

    # fit the skew on the top-20 ranks only: deeper Space-Saving ranks
    # carry eviction-inflated counts (the error bound grows toward the
    # tail), which flattens the fitted slope toward uniform
    _ZIPF_RANKS = 20

    def _zipf(self, top: list[tuple[str, float, float]]) -> float | None:
        return zipf_exponent(
            [c for _k, c, _e in top[: self._ZIPF_RANKS]]
        )

    def _hot_bucket(self) -> tuple[str, float] | None:
        top = self.buckets.top(1)
        return (top[0][0], top[0][1]) if top else None

    def _hot_bucket_rate(self, count: float) -> float:
        """Approximate ops/s of a decayed count: at steady rate r the
        decayed counter equilibrates at r * halflife / ln 2 (the mean
        lifetime), so invert that.  Without decay: count / uptime."""
        if self.halflife:
            return count * _LN2 / self.halflife
        up = max(self.clock() - self.started_at, 1e-9)
        return count / up

    # --- serializations -------------------------------------------------------

    def snapshot(self, top_n: int = 20) -> dict:
        """The local half of `GET /v1/traffic`."""
        top_keys = self.keys.top(top_n)
        top_buckets = self.buckets.top(10)
        total = self.total_ops
        sizes = [
            {"le": SIZE_BOUNDS[i] if i < len(SIZE_BOUNDS) else None,
             "count": c}
            for i, c in enumerate(self.size_counts)
            if c
        ]
        key_total = max(self.keys.total, 1e-9)
        bucket_total = max(self.buckets.total, 1e-9)
        hot_objects = []
        for k, c, e in top_keys:
            b, _, rest = k.partition("/")
            hot_objects.append(
                {
                    "bucket": b,
                    "key": rest,
                    "count": round(c, 2),
                    "errorBound": round(e, 2),
                    "cmEstimate": round(self.key_freq.estimate(k), 2),
                    "share": round(c / key_total, 4),
                }
            )
        return {
            "totalOps": total,
            "opMix": dict(self.ops),
            "readFraction": self.read_fraction(),
            "bytesMoved": self.bytes_moved,
            "hotObjects": hot_objects,
            "hotBuckets": [
                {
                    "bucket": k,
                    "count": round(c, 2),
                    "share": round(c / bucket_total, 4),
                    "opsPerSec": round(self._hot_bucket_rate(c), 4),
                }
                for k, c, _e in top_buckets
            ],
            "sizeHistogram": sizes,
            "zipfS": self._zipf(top_keys),
            "latency": {
                op: {
                    "count": int(n),
                    "meanMs": round(s / n * 1000, 3) if n else None,
                    "maxMs": round(mx * 1000, 3),
                }
                for op, (n, s, mx) in self.latency.items()
                if n
            },
            "decayHalflifeSecs": self.halflife,
            "trackedKeys": len(self.keys),
        }

    def profile(self) -> dict:
        """The REPLAYABLE workload profile (`GET /v1/traffic/profile`):
        everything a generator needs to synthesize statistically-similar
        load — op mix, object-size distribution, popularity skew,
        inter-arrival stats.  Deliberately anonymous: shares and
        distributions, no tenant key names."""
        total = self.total_ops
        n, s, s2 = self._ia
        mean_ia = s / n if n else None
        if n > 1 and mean_ia:
            var = max(0.0, s2 / n - mean_ia * mean_ia)
            cv = round(math.sqrt(var) / mean_ia, 4)
        else:
            cv = None
        top = self.keys.top(50)
        key_total = max(self.keys.total, 1e-9)
        size_n = sum(self.size_counts) or 1
        return {
            "profileVersion": 1,
            "totalOps": total,
            "opMix": {
                op: round(c / total, 4) if total else 0.0
                for op, c in self.ops.items()
            },
            "readFraction": self.read_fraction(),
            "sizeDistribution": {
                "logTwoBuckets": [
                    {
                        "leBytes": (
                            SIZE_BOUNDS[i] if i < len(SIZE_BOUNDS) else None
                        ),
                        "fraction": round(c / size_n, 4),
                    }
                    for i, c in enumerate(self.size_counts)
                    if c
                ],
                "meanBytes": (
                    round(self.bytes_moved / size_n, 1)
                    if sum(self.size_counts)
                    else None
                ),
            },
            "popularity": {
                "zipfS": self._zipf(top),
                "topShares": [
                    round(c / key_total, 4) for _k, c, _e in top[:10]
                ],
                "trackedKeys": len(self.keys),
            },
            "interArrival": {
                "meanSecs": round(mean_ia, 6) if mean_ia else None,
                "cv": cv,
                "opsPerSec": (
                    round(1.0 / mean_ia, 4) if mean_ia else None
                ),
            },
            "decayHalflifeSecs": self.halflife,
        }

    def digest_fields(self, rps: float = 0.0) -> dict:
        """Compact `trf.*` block for the gossiped node digest
        (rpc/telemetry_digest.py; additive keys, DIGEST_VERSION stays
        1).  `rps` is the collector's windowed op rate."""
        reads = sum(self.ops[o] for o in READ_OPS)
        writes = sum(self.ops[o] for o in WRITE_OPS)
        hb = self._hot_bucket()
        out: dict = {
            "ops": self.total_ops,
            "rps": round(rps, 4),
            "rd": reads,
            "wr": writes,
            "ls": self.ops["list"],
            "by": self.bytes_moved,
            "rdf": self.read_fraction(),
            "zipf": self._zipf(self.keys.top(self._ZIPF_RANKS)),
        }
        if hb is not None:
            out["hb"] = hb[0]
            out["hbo"] = round(hb[1], 2)
            out["hbps"] = round(self._hot_bucket_rate(hb[1]), 4)
        return out


# process-wide observatory: the S3 frontends of every in-process node
# feed it and the registry it summarizes for is process-global — per-node
# instances would multiply every observation (PhaseAggregator pattern)
observatory = TrafficObservatory()

_refs = 0


def enable(topk: int | None = None, halflife: float | None = None) -> None:
    """Refcounted attach (every in-process Garage with `[admin]
    traffic_observatory` calls this at start).  Sizing knobs apply only
    on the 0 -> 1 transition — reconfiguring mid-flight would reset the
    sketches under the other nodes."""
    global _refs
    if _refs == 0 and topk is not None:
        observatory.reconfigure(topk, halflife)
    _refs += 1
    observatory.enabled = True


def disable() -> None:
    global _refs
    _refs = max(0, _refs - 1)
    if _refs == 0:
        observatory.enabled = False


# --- cluster rollup + the one serialization per endpoint ----------------------


def slow_peers(garage) -> list[dict]:
    """The slow-rank ranking from this node's viewpoint (peer-health
    piece-fetch EWMAs) — what item 1a's hedged reads will key off."""
    ph = getattr(garage, "peer_health", None)
    if ph is None:
        return []
    return ph.piece_fetch_ranking()


def _traffic_rows(garage) -> list[dict]:
    """Per-node `trf` digest rows from the gossip state.  A digest-less
    old peer (or a peer on a different digest version) renders a clean
    row with `traffic: null` — never an error, never dropped."""
    from .telemetry_digest import _valid_digest

    system = garage.system
    system.expire_node_status()
    local = _valid_digest(garage.telemetry.collect()) or {}
    rows = [
        {
            "id": system.id.hex(),
            "isSelf": True,
            "isUp": True,
            "traffic": local.get("trf"),
        }
    ]
    for pid, (pst, _ts) in sorted(system.node_status.items()):
        d = _valid_digest(pst.telemetry) or {}
        rows.append(
            {
                "id": pid.hex(),
                "isSelf": False,
                "isUp": system.netapp.is_connected(pid),
                "traffic": d.get("trf"),
            }
        )
    return rows


def _num(v) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def traffic_response(garage) -> dict:
    """The one serialization of the traffic observatory, shared by the
    admin HTTP endpoint and the admin-RPC op (key casing cannot drift
    between transports)."""
    rows = _traffic_rows(garage)
    with_trf = [r for r in rows if r.get("traffic")]
    hottest = None
    for r in with_trf:
        t = r["traffic"]
        if t.get("hb") is not None and (
            hottest is None or _num(t.get("hbo")) > _num(hottest["ops"])
        ):
            hottest = {
                "bucket": t["hb"],
                "ops": t.get("hbo"),
                "node": r["id"],
            }
    return {
        "node": garage.node_id.hex(),
        "enabled": _refs > 0,
        "local": observatory.snapshot(),
        "slowPeers": slow_peers(garage),
        "cluster": {
            "nodes": rows,
            "nodesReporting": len(with_trf),
            "aggregate": {
                "opsPerSec": round(
                    sum(_num(r["traffic"].get("rps")) for r in with_trf), 4
                ),
                "ops": sum(_num(r["traffic"].get("ops")) for r in with_trf),
                "bytesMoved": sum(
                    _num(r["traffic"].get("by")) for r in with_trf
                ),
            },
            "hotBucket": hottest,
        },
    }


def profile_response(garage) -> dict:
    return {
        "node": garage.node_id.hex(),
        "enabled": _refs > 0,
        **observatory.profile(),
    }
