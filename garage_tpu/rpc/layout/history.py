"""Layout staging + multi-version history with CRDT update trackers.

Reference src/rpc/layout/history.rs + mod.rs v010: during a layout change,
several versions are simultaneously active — writes must reach a quorum in
EVERY active version's node set, reads use the newest version whose data
has been fully synced, and old versions are retired once every node has
acknowledged the sync.  All of it converges by CRDT merge (gossip), never
consensus.

Trackers (maps node -> version number, merged by per-node max):
  ack      node uses this version for its writes
  sync     node has locally finished syncing data into this version
  sync_ack node has seen that ALL nodes' sync >= this version
"""

from __future__ import annotations

from typing import Any

from ...utils.crdt import Lww, LwwMap
from ...utils.data import blake2sum
from ...utils.serde import pack
from .types import NodeRole, ZoneRedundancy
from .version import LayoutError, LayoutVersion


class UpdateTracker:
    def __init__(self, values: dict[bytes, int] | None = None):
        self.values: dict[bytes, int] = values or {}

    def set_max(self, node: bytes, v: int) -> bool:
        if self.values.get(node, -1) < v:
            self.values[node] = v
            return True
        return False

    def get(self, node: bytes) -> int:
        return self.values.get(node, 0)

    def min_among(self, nodes: list[bytes], default: int) -> int:
        if not nodes:
            return default
        return min(self.values.get(n, 0) for n in nodes)

    def merge(self, other: "UpdateTracker") -> None:
        for n, v in other.values.items():
            self.set_max(n, v)

    def to_obj(self) -> Any:
        return [[n, v] for n, v in sorted(self.values.items())]

    @classmethod
    def from_obj(cls, obj: Any) -> "UpdateTracker":
        return cls({bytes(n): int(v) for n, v in obj})


class LayoutStaging:
    """Staged role changes + parameters, merged CRDT-style across nodes
    before an explicit `apply` (reference mod.rs LayoutStaging)."""

    def __init__(self):
        self.roles: LwwMap = LwwMap()  # node_id -> role obj or None (remove)
        self.parameters: Lww = Lww.raw(0, {"zone_redundancy": ZoneRedundancy.MAXIMUM})

    def stage_role(self, node: bytes, role: NodeRole | None) -> None:
        self.roles.update_in_place(node, role.to_obj() if role else None)

    def merge(self, other: "LayoutStaging") -> None:
        self.roles.merge(other.roles)
        self.parameters.merge(other.parameters)

    def clear(self) -> None:
        self.roles = LwwMap()

    def to_obj(self) -> Any:
        return {"roles": self.roles.to_obj(), "params": self.parameters.to_obj()}

    @classmethod
    def from_obj(cls, obj: Any) -> "LayoutStaging":
        s = cls()
        s.roles = LwwMap.from_obj(obj["roles"])
        s.parameters = Lww.from_obj(obj["params"])
        return s


class LayoutHistory:
    def __init__(self, replication_factor: int):
        self.replication_factor = replication_factor
        self.versions: list[LayoutVersion] = []
        self.ack = UpdateTracker()
        self.sync = UpdateTracker()
        self.sync_ack = UpdateTracker()
        self.staging = LayoutStaging()

    @classmethod
    def initial(cls, replication_factor: int) -> "LayoutHistory":
        h = cls(replication_factor)
        v0 = LayoutVersion(0, replication_factor)
        h.versions = [v0]
        return h

    # --- queries -------------------------------------------------------------

    def current(self) -> LayoutVersion:
        return self.versions[-1]

    def min_stored(self) -> int:
        return self.versions[0].version

    def all_nodes(self) -> list[bytes]:
        nodes: set[bytes] = set()
        for v in self.versions:
            nodes.update(v.all_nodes())
        return sorted(nodes)

    def all_storage_nodes(self) -> list[bytes]:
        nodes: set[bytes] = set()
        for v in self.versions:
            nodes.update(v.storage_nodes())
        return sorted(nodes)

    def read_version(self) -> LayoutVersion:
        """Newest version whose data every storage node has synced
        (reads are safe there); falls back to the oldest active version."""
        for v in reversed(self.versions):
            nodes = v.storage_nodes()
            if self.sync.min_among(nodes, default=v.version) >= v.version:
                return v
        return self.versions[0]

    def read_nodes_of(self, hash32: bytes) -> list[bytes]:
        return self.read_version().nodes_of(hash32)

    def write_sets_of(self, hash32: bytes) -> list[list[bytes]]:
        """One node-set per active version: a write must reach quorum in
        EACH set (reference rpc_helper try_write_many_sets +
        parameters.rs:20-24)."""
        return [v.nodes_of(hash32) for v in self.versions if v.ring_assignment]

    def digest(self) -> bytes:
        return blake2sum(pack(self.to_obj()))

    def placement_digest(self) -> bytes:
        """Digest of the placement-relevant state only: layout versions
        and their ring assignments — NOT the update trackers.  Tracker
        gossip advances constantly during normal operation; anti-entropy
        consumers key off this digest so tracker-only updates don't
        retrigger full sync rounds (each one is ~512 root-compare RPCs
        per table)."""
        return blake2sum(
            pack([
                [v.version, v.node_id_vec, v.ring_assignment]
                for v in self.versions
            ])
        )

    def staging_digest(self) -> bytes:
        return blake2sum(pack(self.staging.to_obj()))

    # --- mutations ------------------------------------------------------------

    def merge(self, other: "LayoutHistory") -> bool:
        """CRDT merge; returns True if anything changed."""
        before = pack(self.to_obj())
        by_ver = {v.version: v for v in self.versions}
        for v in other.versions:
            if v.version not in by_ver:
                by_ver[v.version] = v
        # keep only versions >= the newest min_stored of the two histories
        min_keep = max(self.min_stored(), other.min_stored()) if self.versions and other.versions else 0
        self.versions = [by_ver[k] for k in sorted(by_ver) if k >= min_keep]
        self.ack.merge(other.ack)
        self.sync.merge(other.sync)
        self.sync_ack.merge(other.sync_ack)
        self.staging.merge(other.staging)
        return pack(self.to_obj()) != before

    def apply_staged_changes(self, version: int | None = None) -> tuple["LayoutVersion", list[str]]:
        """Compute the next layout version from current roles + staged
        changes (reference version.rs:281-305 calculate_next_version)."""
        cur = self.current()
        new_roles: dict[bytes, NodeRole] = dict(cur.roles)
        for node, role_obj in self.staging.roles.items():
            if role_obj is None:
                new_roles.pop(bytes(node), None)
            else:
                new_roles[bytes(node)] = NodeRole.from_obj(role_obj)
        params = self.staging.parameters.get()
        next_ver = cur.version + 1
        if version is not None and version != next_ver:
            raise LayoutError(
                f"version mismatch: expected {next_ver} (got {version}); "
                "layout changed concurrently, re-stage and retry"
            )
        lv = LayoutVersion(
            next_ver,
            self.replication_factor,
            params.get("zone_redundancy", ZoneRedundancy.MAXIMUM),
            new_roles,
        )
        report = lv.compute_assignment(cur if cur.ring_assignment else None)
        self.versions.append(lv)
        self.staging.clear()
        self.trim()
        return lv, report

    def revert_staged_changes(self) -> None:
        self.staging.clear()

    # --- tracker updates (called by the local node) ---------------------------

    def update_trackers_of(self, node: bytes) -> None:
        """Advance this node's ack tracker to the newest version, compute
        sync_ack, and retire fully-synced old versions."""
        latest = self.current().version
        self.ack.set_max(node, latest)
        # sync_ack: this node has observed that everyone synced up to v
        all_nodes = self.all_storage_nodes()
        min_sync = self.sync.min_among(all_nodes, default=latest)
        self.sync_ack.set_max(node, min_sync)
        self.trim()

    def mark_synced(self, node: bytes, version: int | None = None) -> None:
        self.sync.set_max(node, version if version is not None else self.current().version)

    def trim(self) -> None:
        """Retire old versions once every node's sync_ack has passed them.
        The bootstrap version (no ring assignment, stores nothing) is
        dropped as soon as a real version exists."""
        while len(self.versions) > 1 and not self.versions[0].ring_assignment:
            self.versions.pop(0)
        while len(self.versions) > 1:
            next_v = self.versions[1].version
            nodes = self.all_storage_nodes()
            if self.sync_ack.min_among(nodes, default=0) >= next_v:
                self.versions.pop(0)
            else:
                break

    # --- serialization --------------------------------------------------------

    def to_obj(self) -> Any:
        return {
            "rf": self.replication_factor,
            "versions": [v.to_obj() for v in self.versions],
            "ack": self.ack.to_obj(),
            "sync": self.sync.to_obj(),
            "sync_ack": self.sync_ack.to_obj(),
            "staging": self.staging.to_obj(),
        }

    @classmethod
    def from_obj(cls, obj: Any) -> "LayoutHistory":
        h = cls(obj["rf"])
        h.versions = [LayoutVersion.from_obj(v) for v in obj["versions"]]
        h.ack = UpdateTracker.from_obj(obj["ack"])
        h.sync = UpdateTracker.from_obj(obj["sync"])
        h.sync_ack = UpdateTracker.from_obj(obj["sync_ack"])
        h.staging = LayoutStaging.from_obj(obj["staging"])
        return h
