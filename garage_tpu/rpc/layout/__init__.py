"""Cluster layout: roles, partition assignment, CRDT history + staging.

Reference src/rpc/layout/ — the heart of Garage's no-consensus design:
placement is a deterministic function of a CRDT-replicated layout, computed
with an optimal min-cost-flow assignment (doc/optimal_layout_report).
"""

from .types import NodeRole, ZoneRedundancy, PARTITION_BITS, N_PARTITIONS
from .version import LayoutVersion
from .history import LayoutHistory, LayoutStaging

__all__ = [
    "NodeRole",
    "ZoneRedundancy",
    "LayoutVersion",
    "LayoutHistory",
    "LayoutStaging",
    "PARTITION_BITS",
    "N_PARTITIONS",
]
