"""Flow algorithms for partition assignment.

The reference (src/rpc/layout/graph_algo.rs) uses Dinic max-flow for the
feasibility dichotomy and cycle-cancelling to minimize rebalance moves.
This implementation keeps Dinic for feasibility but computes the final
assignment as a min-cost max-flow via successive shortest augmenting paths
(SPFA): with the 0/1 move costs used here both approaches yield a
maximum flow of minimum total cost, and successive-shortest-paths is far
better suited to Python (few hundred augmentations of near-linear SPFA).
"""

from __future__ import annotations

from collections import deque

INF = float("inf")


class FlowGraph:
    """Directed flow network with per-edge capacity and cost."""

    def __init__(self, n: int):
        self.n = n
        # edge arrays; edge i's reverse is i^1
        self.to: list[int] = []
        self.cap: list[int] = []
        self.cost: list[int] = []
        self.adj: list[list[int]] = [[] for _ in range(n)]

    def add_edge(self, u: int, v: int, cap: int, cost: int = 0) -> int:
        eid = len(self.to)
        self.to.append(v)
        self.cap.append(cap)
        self.cost.append(cost)
        self.adj[u].append(eid)
        self.to.append(u)
        self.cap.append(0)
        self.cost.append(-cost)
        self.adj[v].append(eid + 1)
        return eid

    def flow_on(self, eid: int) -> int:
        """Flow pushed through forward edge eid = capacity of its reverse."""
        return self.cap[eid ^ 1]

    # --- Dinic max-flow (feasibility checks) --------------------------------

    def max_flow(self, s: int, t: int) -> int:
        flow = 0
        while True:
            level = self._bfs_levels(s, t)
            if level[t] < 0:
                return flow
            it = [0] * self.n
            while True:
                pushed = self._dfs_push(s, t, INF, level, it)
                if not pushed:
                    break
                flow += pushed

    def _bfs_levels(self, s: int, t: int) -> list[int]:
        level = [-1] * self.n
        level[s] = 0
        q = deque([s])
        while q:
            u = q.popleft()
            for eid in self.adj[u]:
                v = self.to[eid]
                if self.cap[eid] > 0 and level[v] < 0:
                    level[v] = level[u] + 1
                    q.append(v)
        return level

    def _dfs_push(self, u: int, t: int, f, level, it) -> int:
        if u == t:
            return int(f)
        while it[u] < len(self.adj[u]):
            eid = self.adj[u][it[u]]
            v = self.to[eid]
            if self.cap[eid] > 0 and level[v] == level[u] + 1:
                pushed = self._dfs_push(v, t, min(f, self.cap[eid]), level, it)
                if pushed:
                    self.cap[eid] -= pushed
                    self.cap[eid ^ 1] += pushed
                    return pushed
            it[u] += 1
        return 0

    # --- min-cost max-flow (final assignment) -------------------------------

    def min_cost_max_flow(self, s: int, t: int) -> tuple[int, int]:
        """Successive shortest augmenting paths (SPFA).  Costs must be
        non-negative on original edges.  Returns (flow, cost)."""
        flow = cost = 0
        while True:
            dist = [INF] * self.n
            in_q = [False] * self.n
            prev_edge = [-1] * self.n
            dist[s] = 0
            q = deque([s])
            in_q[s] = True
            while q:
                u = q.popleft()
                in_q[u] = False
                du = dist[u]
                for eid in self.adj[u]:
                    if self.cap[eid] <= 0:
                        continue
                    v = self.to[eid]
                    nd = du + self.cost[eid]
                    if nd < dist[v]:
                        dist[v] = nd
                        prev_edge[v] = eid
                        if not in_q[v]:
                            q.append(v)
                            in_q[v] = True
            if dist[t] == INF:
                return flow, cost
            # bottleneck along the path
            push = INF
            v = t
            while v != s:
                eid = prev_edge[v]
                push = min(push, self.cap[eid])
                v = self.to[eid ^ 1]
            v = t
            while v != s:
                eid = prev_edge[v]
                self.cap[eid] -= push
                self.cap[eid ^ 1] += push
                v = self.to[eid ^ 1]
            flow += int(push)
            cost += int(push) * int(dist[t])
