"""Layout data types (reference src/rpc/layout/mod.rs:37-150)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

PARTITION_BITS = 8
N_PARTITIONS = 1 << PARTITION_BITS  # 256


def partition_of(hash32: bytes) -> int:
    """Partition = top PARTITION_BITS bits of the key hash
    (reference version.rs:101-104)."""
    return hash32[0]


@dataclass
class NodeRole:
    """Role assigned to a node: zone, capacity in bytes (None = gateway:
    serves API traffic, stores no partitions), free-form tags
    (reference mod.rs:83-94)."""

    zone: str
    capacity: int | None
    tags: list[str] = field(default_factory=list)

    def to_obj(self) -> Any:
        return [self.zone, self.capacity, list(self.tags)]

    @classmethod
    def from_obj(cls, obj: Any) -> "NodeRole":
        return cls(zone=obj[0], capacity=obj[1], tags=list(obj[2]))


class ZoneRedundancy:
    """'maximum' = spread replicas over as many zones as possible;
    AtLeast(x) = each partition must span >= x distinct zones
    (reference mod.rs:143-150)."""

    MAXIMUM = "maximum"

    @staticmethod
    def at_least(x: int) -> int:
        return x

    @staticmethod
    def to_obj(v) -> Any:
        return v

    @staticmethod
    def from_obj(obj) -> Any:
        return obj
