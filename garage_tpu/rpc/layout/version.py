"""One layout version: node roles + optimal partition assignment.

Reference src/rpc/layout/version.rs:305 (`calculate_partition_assignment`):
dichotomy on the partition size × a flow problem, then move-cost
minimization against the previous layout; invariant checker `check()`
(version.rs:177-249).  Flow network shape (version.rs:536-598):

    source --rf--> partition --(rf-z+1)--> (partition, zone) --1--> node
    node --floor(capacity/partition_size)--> sink

A full flow (256 * rf) exists iff every partition can place rf replicas in
>= z distinct zones without exceeding any node's capacity quota.  The
dichotomy finds the largest partition size with a full flow (= maximize
usable capacity); the min-cost pass then prefers keeping a partition's
replicas where the previous layout had them (cost 0) over moving (cost 1).
"""

from __future__ import annotations

import logging
from typing import Any

from ...utils.data import hex_of
from .graph_algo import FlowGraph
from .types import N_PARTITIONS, NodeRole, ZoneRedundancy, partition_of

logger = logging.getLogger("garage.layout")


class LayoutError(Exception):
    pass


class LayoutVersion:
    def __init__(
        self,
        version: int,
        replication_factor: int,
        zone_redundancy=ZoneRedundancy.MAXIMUM,
        roles: dict[bytes, NodeRole] | None = None,
    ):
        self.version = version
        self.replication_factor = replication_factor
        self.zone_redundancy = zone_redundancy
        self.roles: dict[bytes, NodeRole] = roles or {}
        # computed by compute_assignment:
        self.node_id_vec: list[bytes] = []
        self.ring_assignment: list[list[int]] = []  # per partition: rf node idxs
        self.partition_size: int = 0

    # --- queries -------------------------------------------------------------

    def storage_nodes(self) -> list[bytes]:
        return sorted(
            nid for nid, role in self.roles.items() if role.capacity is not None
        )

    def all_nodes(self) -> list[bytes]:
        return sorted(self.roles.keys())

    def nodes_of(self, hash32: bytes) -> list[bytes]:
        """The rf nodes storing this hash (reference version.rs:117-130)."""
        p = partition_of(hash32)
        return self.nodes_of_partition(p)

    def nodes_of_partition(self, p: int) -> list[bytes]:
        if not self.ring_assignment:
            return []
        return [self.node_id_vec[i] for i in self.ring_assignment[p]]

    def effective_zone_redundancy(self) -> int:
        zones = {r.zone for r in self.roles.values() if r.capacity is not None}
        if self.zone_redundancy == ZoneRedundancy.MAXIMUM:
            return min(self.replication_factor, max(1, len(zones)))
        z = int(self.zone_redundancy)
        if z > self.replication_factor:
            raise LayoutError("zone_redundancy cannot exceed replication_factor")
        return z

    # --- assignment ----------------------------------------------------------

    def compute_assignment(self, prev: "LayoutVersion | None" = None) -> list[str]:
        """Compute ring_assignment; returns a human-readable change report.

        Deterministic: same roles + same previous layout => same result on
        every node (required: each node computes placement independently).
        """
        rf = self.replication_factor
        storage = self.storage_nodes()
        if len(storage) < rf:
            raise LayoutError(
                f"not enough storage nodes: {len(storage)} < replication_factor {rf}"
            )
        z = self.effective_zone_redundancy()
        zones = sorted({self.roles[n].zone for n in storage})
        if len(zones) < z:
            raise LayoutError(
                f"not enough zones: {len(zones)} < zone_redundancy {z}"
            )

        # node ordering: storage nodes first (stable hex order), gateways after
        self.node_id_vec = storage + [
            n for n in self.all_nodes() if n not in set(storage)
        ]
        caps = [self.roles[n].capacity for n in storage]

        prev_sets: list[set[int]] = [set() for _ in range(N_PARTITIONS)]
        if prev is not None and prev.ring_assignment:
            idx_of = {n: i for i, n in enumerate(storage)}
            for p in range(N_PARTITIONS):
                for nid in prev.nodes_of_partition(p):
                    if nid in idx_of:
                        prev_sets[p].add(idx_of[nid])

        # dichotomy on partition size: find the largest size with full flow.
        # upper bound: full flow needs sum(floor(cap_i/size)) >= 256*rf
        lo, hi = 1, max(1, sum(caps) // (N_PARTITIONS * rf))
        best = 0
        while lo <= hi:
            mid = (lo + hi) // 2
            if self._feasible(storage, zones, caps, z, mid):
                best = mid
                lo = mid + 1
            else:
                hi = mid - 1
        if best == 0:
            raise LayoutError("cluster capacity too small to place all partitions")
        self.partition_size = best

        # per-node partition-count targets, proportional to capacity inside
        # the zone structure (the balance criterion), then a min-cost flow
        # that meets the targets exactly while minimizing replica moves
        # (the reference achieves the same two-level objective with cycle
        # cancelling, version.rs:642)
        targets = self._balanced_targets(storage, zones, caps, z, best)
        g, part_zone_edges = self._build_graph(
            storage, zones, caps, z, best, prev_sets, sink_caps=targets
        )
        flow, cost = g.min_cost_max_flow(0, 1)
        if flow != N_PARTITIONS * rf:
            # integer rounding of targets can (rarely) be infeasible against
            # the per-partition zone constraints: fall back to plain quotas
            logger.warning("target-constrained flow infeasible; using quotas")
            g, part_zone_edges = self._build_graph(
                storage, zones, caps, z, best, prev_sets
            )
            flow, cost = g.min_cost_max_flow(0, 1)
        if flow != N_PARTITIONS * rf:
            raise LayoutError("internal error: final flow not full")

        self.ring_assignment = [[] for _ in range(N_PARTITIONS)]
        for (p, _zi, ni), eid in part_zone_edges.items():
            if g.flow_on(eid) > 0:
                self.ring_assignment[p].append(ni)
        for p in range(N_PARTITIONS):
            # deterministic replica order: previous nodes first, then by index
            self.ring_assignment[p].sort(
                key=lambda ni: (0 if ni in prev_sets[p] else 1, ni)
            )
            if len(self.ring_assignment[p]) != rf:
                raise LayoutError(f"partition {p} got {len(self.ring_assignment[p])} replicas")

        moved = sum(
            len(set(self.ring_assignment[p]) - prev_sets[p])
            for p in range(N_PARTITIONS)
            if prev_sets[p]
        )
        report = [
            f"partition size: {self.partition_size} bytes",
            f"usable capacity per node: "
            + ", ".join(
                f"{hex_of(n)[:8]}={self._n_partitions_of(i)}p"
                for i, n in enumerate(storage)
            ),
            f"replica moves vs previous layout: {moved} (cost {cost})",
        ]
        return report

    def _n_partitions_of(self, node_idx: int) -> int:
        return sum(1 for a in self.ring_assignment if node_idx in a)

    def _graph_vertices(self, storage, zones):
        # 0 = source, 1 = sink, partitions 2..2+256,
        # (partition, zone) pairs, then nodes
        base_pz = 2 + N_PARTITIONS
        n_pz = N_PARTITIONS * len(zones)
        base_nodes = base_pz + n_pz
        n_vertices = base_nodes + len(storage)
        return base_pz, base_nodes, n_vertices

    def _build_graph(self, storage, zones, caps, z, psize, prev_sets, sink_caps=None):
        rf = self.replication_factor
        zone_idx = {zn: i for i, zn in enumerate(zones)}
        base_pz, base_nodes, n_v = self._graph_vertices(storage, zones)
        g = FlowGraph(n_v)
        for p in range(N_PARTITIONS):
            g.add_edge(0, 2 + p, rf)
        part_zone_edges: dict[tuple[int, int, int], int] = {}
        for p in range(N_PARTITIONS):
            for zi in range(len(zones)):
                g.add_edge(2 + p, base_pz + p * len(zones) + zi, rf - z + 1)
        for ni, n in enumerate(storage):
            zi = zone_idx[self.roles[n].zone]
            for p in range(N_PARTITIONS):
                cost = 0 if ni in prev_sets[p] else 1
                eid = g.add_edge(
                    base_pz + p * len(zones) + zi, base_nodes + ni, 1, cost
                )
                part_zone_edges[(p, zi, ni)] = eid
            quota = caps[ni] // psize if sink_caps is None else sink_caps[ni]
            g.add_edge(base_nodes + ni, 1, quota)
        return g, part_zone_edges

    def _balanced_targets(self, storage, zones, caps, z, psize) -> list[int]:
        """Per-node partition-count targets: allocate the 256*rf replica
        slots to zones proportionally to zone capacity (bounded by the
        per-partition zone cap rf-z+1 and zone quota), then within each
        zone to nodes proportionally to capacity (bounded by quota and the
        one-replica-per-partition limit)."""
        rf = self.replication_factor
        total = N_PARTITIONS * rf
        quotas = [min(caps[i] // psize, N_PARTITIONS) for i in range(len(storage))]
        zone_nodes: dict[str, list[int]] = {}
        for i, n in enumerate(storage):
            zone_nodes.setdefault(self.roles[n].zone, []).append(i)
        zone_caps = {zn: sum(caps[i] for i in idxs) for zn, idxs in zone_nodes.items()}
        zone_uppers = {
            zn: min(N_PARTITIONS * (rf - z + 1), sum(quotas[i] for i in idxs))
            for zn, idxs in zone_nodes.items()
        }
        zone_alloc = _proportional_allocation(
            total,
            [zone_caps[zn] for zn in zones],
            [zone_uppers[zn] for zn in zones],
        )
        targets = [0] * len(storage)
        for zi, zn in enumerate(zones):
            idxs = zone_nodes[zn]
            alloc = _proportional_allocation(
                zone_alloc[zi],
                [caps[i] for i in idxs],
                [quotas[i] for i in idxs],
            )
            for j, i in enumerate(idxs):
                targets[i] = alloc[j]
        return targets

    def _feasible(self, storage, zones, caps, z, psize) -> bool:
        g, _ = self._build_graph(storage, zones, caps, z, psize, [set()] * N_PARTITIONS)
        return g.max_flow(0, 1) == N_PARTITIONS * self.replication_factor



    # --- invariants (reference version.rs:177-249) ---------------------------

    def check(self) -> None:
        rf = self.replication_factor
        storage = self.storage_nodes()
        n_storage = len(storage)
        assert len(self.ring_assignment) == N_PARTITIONS, "wrong partition count"
        z = self.effective_zone_redundancy()
        for p, nodes in enumerate(self.ring_assignment):
            assert len(nodes) == rf, f"partition {p}: {len(nodes)} != rf"
            assert len(set(nodes)) == rf, f"partition {p}: duplicate replicas"
            assert all(0 <= i < n_storage for i in nodes), (
                f"partition {p}: gateway or unknown node assigned"
            )
            pzones = {self.roles[self.node_id_vec[i]].zone for i in nodes}
            assert len(pzones) >= z, f"partition {p}: zone redundancy violated"
        # capacity quota: no node holds more partitions than its capacity allows
        for i, n in enumerate(storage):
            quota = self.roles[n].capacity // self.partition_size
            held = self._n_partitions_of(i)
            assert held <= quota, f"node {hex_of(n)[:8]} over quota: {held} > {quota}"

    # --- serialization -------------------------------------------------------

    def to_obj(self) -> Any:
        return {
            "version": self.version,
            "rf": self.replication_factor,
            "zr": ZoneRedundancy.to_obj(self.zone_redundancy),
            "roles": [[n, r.to_obj()] for n, r in sorted(self.roles.items())],
            "node_id_vec": list(self.node_id_vec),
            "ring": [list(a) for a in self.ring_assignment],
            "psize": self.partition_size,
        }

    @classmethod
    def from_obj(cls, obj: Any) -> "LayoutVersion":
        lv = cls(
            version=obj["version"],
            replication_factor=obj["rf"],
            zone_redundancy=ZoneRedundancy.from_obj(obj["zr"]),
            roles={bytes(n): NodeRole.from_obj(r) for n, r in obj["roles"]},
        )
        lv.node_id_vec = [bytes(n) for n in obj["node_id_vec"]]
        lv.ring_assignment = [list(a) for a in obj["ring"]]
        lv.partition_size = obj["psize"]
        return lv


def _proportional_allocation(
    total: int, weights: list[int], uppers: list[int]
) -> list[int]:
    """Integer allocation of `total` units proportional to `weights`,
    clipped at `uppers` with water-filling redistribution; largest-remainder
    rounding, ties broken by index (deterministic on all nodes)."""
    n = len(weights)
    alloc = [0] * n
    active = [i for i in range(n) if uppers[i] > 0]
    remaining = total
    while remaining > 0 and active:
        wsum = sum(weights[i] for i in active)
        if wsum == 0:
            # no capacity weights left: spread evenly
            shares = {i: remaining / len(active) for i in active}
        else:
            shares = {i: remaining * weights[i] / wsum for i in active}
        clipped = [i for i in active if alloc[i] + shares[i] >= uppers[i]]
        if clipped:
            for i in clipped:
                remaining -= uppers[i] - alloc[i]
                alloc[i] = uppers[i]
            active = [i for i in active if i not in set(clipped)]
            continue
        # no clipping: integer-round shares by largest remainder
        floors = {i: int(shares[i]) for i in active}
        rem = remaining - sum(floors.values())
        order = sorted(active, key=lambda i: (-(shares[i] - floors[i]), i))
        for i in active:
            alloc[i] += floors[i]
        for i in order[:rem]:
            alloc[i] += 1
        remaining = 0
    if remaining > 0:
        raise LayoutError("proportional allocation infeasible (bounds too tight)")
    return alloc
