"""LayoutManager: owns the replicated LayoutHistory, persists it, gossips
it, and notifies subscribers on change.

Reference src/rpc/layout/manager.rs:21-120: layouts propagate via
SystemRpc::{Pull,Advertise}ClusterLayout; merging is pure CRDT so any
gossip order converges.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from ...utils.migrate import Migratable
from .history import LayoutHistory
from .types import NodeRole

logger = logging.getLogger("garage.layout")


class PersistedLayout(Migratable):
    VERSION_MARKER = b"GT0layout"

    def __init__(self, history: LayoutHistory):
        self.history = history

    def to_obj(self) -> Any:
        return self.history.to_obj()

    @classmethod
    def from_obj(cls, obj: Any) -> "PersistedLayout":
        return cls(LayoutHistory.from_obj(obj))


class LayoutManager:
    def __init__(self, node_id: bytes, replication_factor: int, persister=None):
        self.node_id = node_id
        self.persister = persister
        loaded = persister.load() if persister else None
        if loaded is not None:
            self.history = loaded.history
            if self.history.replication_factor != replication_factor:
                raise ValueError(
                    f"replication_factor changed from "
                    f"{self.history.replication_factor} to {replication_factor}; "
                    "this is not supported"
                )
        else:
            self.history = LayoutHistory.initial(replication_factor)
        # merge_remote/local_update are synchronous on the event loop, which
        # is what serializes them — no lock needed
        self.change_listeners: list[Callable[[], None]] = []
        # layout-sync coordination (reference src/rpc/layout/manager.rs:
        # each table syncer reports completed sync rounds; once EVERY
        # registered component has synced up to version v, this node's
        # sync tracker advances, which — gossiped and acked by the other
        # nodes — lets trim() retire old versions and read_version() move
        # forward).  name -> highest cleanly-synced layout version.
        self._sync_components: dict[str, int] = {}

    # --- local views ---------------------------------------------------------

    def digest(self) -> bytes:
        return self.history.digest()

    def save(self) -> None:
        if self.persister:
            self.persister.save(PersistedLayout(self.history))

    def subscribe(self, fn: Callable[[], None]) -> None:
        self.change_listeners.append(fn)

    def _notify(self) -> None:
        for fn in self.change_listeners:
            try:
                fn()
            except Exception:  # noqa: BLE001
                logger.exception("layout change listener failed")

    # --- merge / advertise ---------------------------------------------------

    def merge_remote(self, obj: Any) -> bool:
        """Merge a layout advertised by a peer; returns True if changed."""
        other = LayoutHistory.from_obj(obj)
        if other.replication_factor != self.history.replication_factor:
            logger.error(
                "peer advertises replication_factor %d != ours %d; ignoring",
                other.replication_factor,
                self.history.replication_factor,
            )
            return False
        changed = self.history.merge(other)
        if changed:
            self.history.update_trackers_of(self.node_id)
            self.save()
            self._notify()
        return changed

    def local_update(self, mutate: Callable[[LayoutHistory], Any]) -> Any:
        """Apply a local mutation (stage/apply/revert/tracker update),
        persist and notify."""
        res = mutate(self.history)
        self.history.update_trackers_of(self.node_id)
        self.save()
        self._notify()
        return res

    # --- convenience for the CLI/admin paths ---------------------------------

    def stage_role(self, node: bytes, role: NodeRole | None) -> None:
        self.local_update(lambda h: h.staging.stage_role(node, role))

    def apply_staged(self, version: int | None = None):
        return self.local_update(lambda h: h.apply_staged_changes(version))

    def revert_staged(self) -> None:
        self.local_update(lambda h: h.revert_staged_changes())

    def mark_synced(self, version: int | None = None) -> None:
        self.local_update(lambda h: h.mark_synced(self.node_id, version))

    # --- sync completion tracking --------------------------------------------

    def register_sync_component(self, name: str) -> None:
        """Declare a component whose sync completion gates layout-version
        retirement.  All components must be registered before workers
        start reporting (Garage wires every table before spawn)."""
        self._sync_components.setdefault(name, 0)

    def component_synced(self, name: str, version: int) -> None:
        """A component finished a CLEAN sync round that began at layout
        `version`; advance this node's sync tracker to the minimum across
        all components."""
        if self._sync_components.get(name, 0) >= version:
            return
        self._sync_components[name] = version
        v = min(self._sync_components.values())
        if v > self.history.sync.get(self.node_id):
            self.mark_synced(v)
