"""System: cluster membership, status gossip, health.

Reference src/rpc/system.rs:87-179: persists the peer list, exchanges
`NodeStatus` (hostname, version, layout digest, disk space) with all
connected peers every STATUS_EXCHANGE_INTERVAL, runs a discovery loop over
bootstrap peers, pulls/advertises cluster layouts when digests differ, and
computes `ClusterHealth` from per-partition quorum availability.
"""

from __future__ import annotations

import asyncio
import logging
import shutil
import socket
import time
from dataclasses import dataclass, field
from typing import Any

from ..net.message import PRIO_HIGH, Req, Resp
from ..utils.background import spawn
from ..utils.data import blake2sum
from ..utils.serde import pack
from ..net.netapp import NetApp
from ..net.peering import PeeringManager
from ..utils.migrate import Migratable
from ..utils.persister import Persister
from .layout.manager import LayoutManager
from .layout.types import N_PARTITIONS
from .replication_mode import ReplicationMode
from .transition import OFFSET_ALPHA, estimate_offset

logger = logging.getLogger("garage.system")

STATUS_EXCHANGE_INTERVAL = 10.0
DISCOVERY_INTERVAL = 60.0
ADVERTISE_COALESCE = 0.2  # burst-coalescing window for layout gossip
# node_status entries older than this are aged out (a dead peer stops
# refreshing; keeping it forever made the rollup and `garage status`
# show departed nodes as current indefinitely)
NODE_STATUS_EXPIRY = 6 * STATUS_EXCHANGE_INTERVAL


@dataclass
class NodeStatus:
    hostname: str
    version: str
    layout_digest: bytes
    meta_disk_avail: tuple[int, int] | None = None  # (free, total)
    data_disk_avail: tuple[int, int] | None = None
    replication_factor: int = 1
    # cluster telemetry plane (rpc/telemetry_digest.py): the sender's
    # pre-aggregated telemetry digest, piggybacked on the status
    # exchange.  None from peers running a version without the field.
    telemetry: Any = None

    def to_obj(self) -> Any:
        obj = {
            "h": self.hostname,
            "v": self.version,
            "ld": self.layout_digest,
            "md": list(self.meta_disk_avail) if self.meta_disk_avail else None,
            "dd": list(self.data_disk_avail) if self.data_disk_avail else None,
            "rf": self.replication_factor,
        }
        if self.telemetry is not None:
            obj["tm"] = self.telemetry
        return obj

    @classmethod
    def from_obj(cls, obj: Any) -> "NodeStatus":
        return cls(
            hostname=obj["h"],
            version=obj["v"],
            layout_digest=bytes(obj["ld"]),
            meta_disk_avail=tuple(obj["md"]) if obj.get("md") else None,
            data_disk_avail=tuple(obj["dd"]) if obj.get("dd") else None,
            replication_factor=obj.get("rf", 1),
            telemetry=obj.get("tm"),  # tolerant: old peers don't send it
        )


@dataclass
class ClusterHealth:
    status: str  # healthy | degraded | unavailable
    known_nodes: int = 0
    connected_nodes: int = 0
    storage_nodes: int = 0
    storage_nodes_up: int = 0
    partitions: int = N_PARTITIONS
    partitions_quorum: int = 0
    partitions_all_ok: int = 0
    # MAD-flagged sick nodes (rpc/telemetry_digest.py detect_outliers);
    # empty when fewer than 3 nodes report digests
    outlier_nodes: list[str] = field(default_factory=list)


class PersistedPeers(Migratable):
    VERSION_MARKER = b"GT0peers"

    def __init__(self, peers: list[tuple[bytes, tuple[str, int]]]):
        self.peers = peers

    def to_obj(self) -> Any:
        return [[p, list(a)] for p, a in self.peers]

    @classmethod
    def from_obj(cls, obj: Any) -> "PersistedPeers":
        return cls([(bytes(p), (a[0], int(a[1]))) for p, a in obj])


class System:
    """Composition of NetApp + PeeringManager + LayoutManager + gossip."""

    def __init__(
        self,
        netapp: NetApp,
        layout_manager: LayoutManager,
        replication_mode: ReplicationMode,
        bootstrap: list[tuple[bytes, tuple[str, int]]] | None = None,
        # the annotation doubles as the analyzer's receiver-type source:
        # `self.peer_persister.save` resolves into Persister (ISSUE 10)
        peer_persister: Persister | None = None,
        metadata_dir: str | None = None,
        data_dirs: list[str] | None = None,
        public_addr: tuple[str, int] | None = None,
        discovery: list | None = None,
    ):
        self.netapp = netapp
        self.id = netapp.id
        self.layout_manager = layout_manager
        self.replication_mode = replication_mode
        self.peer_persister = peer_persister
        self.metadata_dir = metadata_dir
        self.data_dirs = data_dirs or []
        # external publishers (Consul/Kubernetes, rpc/discovery.py)
        self.discovery = discovery or []
        self.public_addr = public_addr
        persisted = peer_persister.load() if peer_persister else None
        known = list(bootstrap or [])
        if persisted:
            known.extend(persisted.peers)
        self.peering = PeeringManager(netapp, known, public_addr=public_addr)
        self.node_status: dict[bytes, tuple[NodeStatus, float]] = {}
        # cluster telemetry plane: model/garage.py points this at its
        # DigestCollector.collect so every outgoing NodeStatus carries
        # the local digest (None = no collector, e.g. bare System tests)
        self.telemetry_collector = None
        # rebalance observatory (rpc/transition.py): model/garage.py
        # points these at its TransitionTracker / flight-event bank
        self.transition_tracker = None
        self.events_collector = None
        # NTP-style per-peer clock offsets estimated from the status
        # exchange: peer id -> {"offset": s, "rtt": s, "at": monotonic}
        self.clock_offsets: dict[bytes, dict] = {}
        self.wallclock = time.time  # injectable for skew tests
        self.status_expiry = NODE_STATUS_EXPIRY
        self._tasks: list[asyncio.Task] = []
        # coalesced layout gossip state (see _advertise_loop)
        self._adv_event = asyncio.Event()
        self._adv_sem = asyncio.Semaphore(8)
        self._advertised: dict[bytes, bytes] = {}  # peer -> last digest sent
        self._adv_inflight: set[bytes] = set()
        self._adv_latest: bytes | None = None  # last wave's snapshot digest

        self.status_ep = netapp.endpoint("rpc/system/status")
        self.status_ep.set_handler(self._handle_status)
        self.pull_layout_ep = netapp.endpoint("rpc/system/pull_layout")
        self.pull_layout_ep.set_handler(self._handle_pull_layout)
        self.adv_layout_ep = netapp.endpoint("rpc/system/advertise_layout")
        self.adv_layout_ep.set_handler(self._handle_advertise_layout)
        self.events_ep = netapp.endpoint("rpc/system/events")
        self.events_ep.set_handler(self._handle_events)
        layout_manager.subscribe(self._on_layout_change)

    # --- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self.peering.start()
        self._tasks.append(asyncio.create_task(self._status_loop()))
        self._tasks.append(asyncio.create_task(self._discovery_loop()))
        self._tasks.append(asyncio.create_task(self._advertise_loop()))

    async def stop(self) -> None:
        from ..utils.aio import reap

        await reap(self._tasks, log=logger, what="system loop")
        for d in self.discovery:
            try:
                await d.close()
            except Exception as e:  # noqa: BLE001
                logger.debug(
                    "discovery %s close failed: %r", type(d).__name__, e
                )
        await self.peering.stop()

    # --- status --------------------------------------------------------------

    def local_status(self) -> NodeStatus:
        def disk(path):
            try:
                u = shutil.disk_usage(path)
                return (u.free, u.total)
            except OSError:
                return None

        telemetry = None
        if self.telemetry_collector is not None:
            try:
                telemetry = self.telemetry_collector()
            except Exception:  # noqa: BLE001 — status gossip must survive
                logger.exception("telemetry digest collection failed")
        return NodeStatus(
            hostname=socket.gethostname(),
            version="garage-tpu/0.1.0",
            layout_digest=self.layout_manager.digest(),
            meta_disk_avail=disk(self.metadata_dir) if self.metadata_dir else None,
            data_disk_avail=disk(self.data_dirs[0]) if self.data_dirs else None,
            replication_factor=self.replication_mode.replication_factor,
            telemetry=telemetry,
        )

    async def _handle_status(self, from_id: bytes, req: Req) -> Resp:
        st = NodeStatus.from_obj(req.body)
        self._record_status(from_id, st)
        # the reply carries a fresh wall-clock stamp for the caller's
        # NTP-style offset estimate (rpc/transition.py estimate_offset)
        return Resp({**self.local_status().to_obj(), "ts": self.wallclock()})

    def _note_peer_clock(
        self, pid: bytes, t0: float, t_peer: float, t3: float
    ) -> None:
        """EWMA one NTP-style offset sample for a peer (one sample per
        status exchange — the merged event timeline's ordering and the
        `SKEW!` flag both hang off this estimate)."""
        off, rtt = estimate_offset(t0, t_peer, t3)
        prev = self.clock_offsets.get(pid)
        if prev is not None:
            off = OFFSET_ALPHA * off + (1 - OFFSET_ALPHA) * prev["offset"]
            rtt = OFFSET_ALPHA * rtt + (1 - OFFSET_ALPHA) * prev["rtt"]
        self.clock_offsets[pid] = {
            "offset": off, "rtt": rtt, "at": time.monotonic()
        }

    async def _handle_events(self, from_id: bytes, req: Req) -> Resp:
        """Federated event timeline (rpc/transition.py): serve this
        node's banked flight events to a peer's admin fan-out."""
        body = req.body if isinstance(req.body, dict) else {}
        collector = self.events_collector
        if collector is None:
            return Resp([])
        return Resp(collector(
            since=float(body.get("since", 0.0) or 0.0),
            min_severity=str(body.get("sev", "info")),
        ))

    def _record_status(self, from_id: bytes, st: NodeStatus) -> None:
        self.node_status[from_id] = (st, time.monotonic())
        if st.layout_digest != self.layout_manager.digest():
            spawn(self._pull_layout_from(from_id))

    async def _pull_layout_from(self, node: bytes) -> None:
        try:
            resp = await self.pull_layout_ep.call(node, None, prio=PRIO_HIGH)
            if resp.body is not None:
                self.layout_manager.merge_remote(resp.body)
        except Exception as e:  # noqa: BLE001
            logger.debug("layout pull from %s failed: %r", node.hex()[:8], e)

    async def _handle_pull_layout(self, from_id: bytes, req: Req) -> Resp:
        return Resp(self.layout_manager.history.to_obj())

    async def _handle_advertise_layout(self, from_id: bytes, req: Req) -> Resp:
        self.layout_manager.merge_remote(req.body)
        return Resp(None)

    def _on_layout_change(self) -> None:
        # Coalesced gossip: mark dirty and let _advertise_loop push ONE
        # snapshot per burst.  Broadcasting on every CRDT delta is an
        # amplification bomb on a full mesh: each of n nodes' tracker
        # bumps re-triggers an n-peer broadcast on each of n nodes —
        # a 21-node layout apply was observed to pile up >13k concurrent
        # advertise tasks and starve the event loop for ~70 s.
        self._adv_event.set()

    async def _advertise_loop(self) -> None:
        """Push the layout to peers when it changed, one wave per burst.
        Per-peer digest suppression avoids re-sending a snapshot the peer
        was already sent; the status loop's digest-mismatch pull is the
        convergence backstop for lost adverts.  Waves never await their
        sends: a hung peer occupies one in-flight slot, it does not delay
        the next wave to the healthy peers."""
        while True:
            await self._adv_event.wait()
            await asyncio.sleep(ADVERTISE_COALESCE)
            self._adv_event.clear()
            try:
                obj = self.layout_manager.history.to_obj()
                # same bytes as layout_manager.digest() without packing
                # the history a second time (waves fire every 0.2 s
                # under tracker churn)
                digest = blake2sum(pack(obj))
                self._adv_latest = digest
                connected = set(self.peering.connected_peers())
                # drop suppression state for departed peers (a reconnecting
                # peer with an unchanged digest is covered by the status
                # loop's pull backstop)
                self._advertised = {
                    p: d for p, d in self._advertised.items() if p in connected
                }
                for p in connected:
                    if (
                        self._advertised.get(p) != digest
                        and p not in self._adv_inflight
                    ):
                        self._adv_inflight.add(p)
                        spawn(self._advertise_one(p, obj, digest))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("advertise loop error")

    async def _advertise_one(self, pid: bytes, obj: Any, digest: bytes) -> None:
        try:
            async with self._adv_sem:  # bounded fan-out on wide meshes
                await self.adv_layout_ep.call(pid, obj, prio=PRIO_HIGH, timeout=10.0)
            self._advertised[pid] = digest
        except Exception as e:  # noqa: BLE001
            logger.debug("layout advertise to %s failed: %r", pid.hex()[:8], e)
        finally:
            self._adv_inflight.discard(pid)
            # the layout may have moved on while this send was in flight
            # (waves skip in-flight peers): retrigger so the peer gets
            # the newer snapshot
            if digest != self._adv_latest:
                self._adv_event.set()

    # --- loops ---------------------------------------------------------------

    async def status_exchange_once(self) -> None:
        """One status-gossip wave: push our NodeStatus (+ telemetry
        digest) to every connected peer, record theirs, age out entries
        from departed peers.  The status loop's body; tests drive it
        directly to converge a cluster without waiting out the
        exchange interval."""
        st = self.local_status().to_obj()

        async def exchange(pid):
            try:
                t0 = self.wallclock()
                resp = await self.status_ep.call(
                    pid, {**st, "ts": t0}, prio=PRIO_HIGH, timeout=10.0
                )
                t3 = self.wallclock()
                self._record_status(pid, NodeStatus.from_obj(resp.body))
                ts = (
                    resp.body.get("ts")
                    if isinstance(resp.body, dict) else None
                )
                if ts is not None:
                    self._note_peer_clock(pid, t0, float(ts), t3)
            except Exception as e:  # noqa: BLE001 — one dead peer must not
                # stall the wave, but the miss is worth a debug line
                logger.debug(
                    "status exchange with %s failed: %r", pid.hex()[:8], e
                )

        # concurrent fan-out: one hung peer must not delay the rest
        await asyncio.gather(
            *[exchange(pid) for pid in self.peering.connected_peers()]
        )
        self.expire_node_status()

    def expire_node_status(self) -> None:
        """Age out status entries no longer being refreshed.  A
        connected peer re-records every exchange; an entry that is BOTH
        stale and disconnected belongs to a departed node — dropping it
        removes the node from the telemetry rollup and from `garage
        status` hostnames.  (Digest rows are rendered inline from this
        map, never registered as per-node gauges, so there is nothing
        else to unregister.)"""
        now = time.monotonic()
        for pid in [
            p
            for p, (_st, ts) in self.node_status.items()
            if now - ts > self.status_expiry and not self.netapp.is_connected(p)
        ]:
            logger.info(
                "aging out status of departed node %s", pid.hex()[:8]
            )
            del self.node_status[pid]
            self.clock_offsets.pop(pid, None)

    async def _status_loop(self) -> None:
        while True:
            try:
                await self.status_exchange_once()
            except Exception:  # noqa: BLE001
                logger.exception("status loop error")
            await asyncio.sleep(STATUS_EXCHANGE_INTERVAL)

    async def _discovery_loop(self) -> None:
        while True:
            try:
                if self.peer_persister:
                    peers = [
                        (p.id, p.addr)
                        for p in self.peering.peers.values()
                        if p.addr is not None
                    ]
                    # off-loop: the peer-list fsync used to run on the
                    # event loop every discovery tick (loop-blocker,
                    # visible only since receiver-type resolution)
                    await self.peer_persister.save_in_thread(
                        PersistedPeers(peers)
                    )
                await self._external_discovery()
            except Exception:  # noqa: BLE001
                logger.exception("discovery loop error")
            await asyncio.sleep(DISCOVERY_INTERVAL)

    async def _external_discovery(self) -> None:
        """Publish this node to + learn peers from external publishers
        (reference system.rs discovery via consul.rs / kubernetes.rs)."""
        if not self.discovery:
            return
        my_addr = self.public_addr or self.netapp.bind_addr
        if my_addr is not None and my_addr[0] in ("0.0.0.0", "::", ""):
            # a wildcard bind address is meaningless to peers — publishing
            # it would make everyone dial themselves
            logger.warning(
                "discovery: rpc_public_addr not set and bind address is "
                "%s; not publishing this node", my_addr[0],
            )
            my_addr = None
        for d in self.discovery:
            try:
                if my_addr is not None:
                    await d.publish(self.id, my_addr)
                for node_id, addr in await d.get_nodes():
                    if node_id == self.id or self.netapp.is_connected(node_id):
                        continue
                    try:
                        await self.netapp.connect(addr, node_id)
                    except Exception as e:  # noqa: BLE001
                        logger.debug(
                            "discovered peer %s @ %s unreachable: %r",
                            node_id.hex()[:8], addr, e,
                        )
            except Exception as e:  # noqa: BLE001
                logger.warning("external discovery (%s) failed: %r",
                               type(d).__name__, e)

    # --- health --------------------------------------------------------------

    def health(self, outlier_nodes: list[str] | None = None) -> ClusterHealth:
        """`outlier_nodes`: pass a precomputed set (telemetry rollup /
        federated exposition already ran the MAD detector on the same
        rows) to avoid re-deriving it; None computes it here."""
        layout = self.layout_manager.history
        storage_nodes = layout.all_storage_nodes()
        up = {
            n
            for n in storage_nodes
            if n == self.id or self.netapp.is_connected(n)
        }
        quorum = self.replication_mode.write_quorum()
        n_quorum = n_all = 0
        cur = layout.current()
        if cur.ring_assignment:
            for p in range(N_PARTITIONS):
                nodes = set(cur.nodes_of_partition(p))
                # during migration a partition must be writable in every
                # active version's node set
                ok_all = all(
                    sum(1 for n in v.nodes_of_partition(p) if n in up) >= quorum
                    for v in layout.versions
                    if v.ring_assignment
                )
                if ok_all:
                    n_quorum += 1
                if nodes <= up:
                    n_all += 1
        status = "healthy"
        if cur.ring_assignment:
            if n_quorum < N_PARTITIONS:
                status = "unavailable"
            elif n_all < N_PARTITIONS or len(up) < len(storage_nodes):
                status = "degraded"
        known = self.peering.peers
        if outlier_nodes is None:
            from .telemetry_digest import outlier_node_ids

            outlier_nodes = outlier_node_ids(self)
        return ClusterHealth(
            status=status,
            known_nodes=len(known) + 1,
            connected_nodes=len(self.peering.connected_peers()) + 1,
            storage_nodes=len(storage_nodes),
            storage_nodes_up=len(up),
            partitions_quorum=n_quorum,
            partitions_all_ok=n_all,
            outlier_nodes=outlier_nodes,
        )
