"""Overload-control plane, reaction side: the SLO-driven shedding
controller.

A background `Worker` closes the loop from observation (PR 5's
`SloTracker` burn rates, the event-loop-lag p99 from the local
telemetry digest) to action: when the node is burning its SLO budget
faster than it can afford, the controller walks a DECLARED degradation
ladder — cheapest, most reversible step first — and walks back down
once the budget stops burning:

    level 1  repair-slow      repair tranquility x4, bytes-in-flight /4
    level 2  sync-stretch     table anti-entropy interval x4
    level 3  scrub-pause      pause the scrub worker
    level 4  shed-anonymous   admission sheds tier 3 (anonymous)
    level 5  shed-list        admission sheds tiers >= 2 (list/batch)
    level 6  shed-write       admission sheds tiers >= 1 (writes)

Interactive traffic (tier 0) is never shed by the ladder — at level 6
the node serves reads, queues them briefly under the in-flight cap, and
turns everything else away with `503 SlowDown`.

Every actuator is one of the live `BgVars` / worker commands that
already exist (repair-tranquility, repair-bytes-in-flight,
sync-interval-secs, scrub pause) plus the admission controller's shed
tier (api/overload.py) — the controller saves each knob's prior value
when a step applies and restores it exactly on the way down.

Hysteresis (no flapping):
  - step UP at most one level per check interval, only while the signal
    says overloaded (burn > `ladder_burn_up` or loop lag p99 over its
    threshold);
  - step DOWN one level only after `ladder_hold_secs` of CONTINUOUS
    recovery (burn < `ladder_burn_down` and lag below half the
    threshold), and the hold restarts after each step down;
  - the gray zone between the two thresholds holds position.

Every transition is logged with its reason and counted in
`overload_ladder_steps_total{direction}`; the current level is the
`overload_ladder_level` gauge, the gossiped digest's `ovl.lvl`, and the
federated `cluster_node_overload_ladder_level` — a shedding node is
visible cluster-wide in `cluster top`.
"""

from __future__ import annotations

import logging
import time
from typing import Any

from ..utils.background import Worker, WorkerState
from ..utils.metrics import registry

logger = logging.getLogger("garage.shedding")


# --- ladder steps -------------------------------------------------------------


class _Step:
    """One rung: apply() returns an opaque saved-state token that
    revert() consumes.  Both are best-effort: a missing actuator (e.g.
    scrub disabled) must not wedge the ladder above or below it."""

    name = "step"

    def apply(self, garage) -> Any:
        raise NotImplementedError

    def revert(self, garage, saved: Any) -> None:
        raise NotImplementedError


class _RepairSlow(_Step):
    name = "repair-slow"

    def apply(self, garage) -> Any:
        bv = garage.bg_vars
        saved = (bv.get("repair-tranquility"), bv.get("repair-bytes-in-flight"))
        bv.set("repair-tranquility", str(max(int(saved[0]) * 4, 8)))
        bv.set(
            "repair-bytes-in-flight",
            str(max(int(saved[1]) // 4, 1024 * 1024)),
        )
        return saved

    def revert(self, garage, saved: Any) -> None:
        garage.bg_vars.set("repair-tranquility", saved[0])
        garage.bg_vars.set("repair-bytes-in-flight", saved[1])


class _SyncStretch(_Step):
    name = "sync-stretch"

    def apply(self, garage) -> Any:
        bv = garage.bg_vars
        saved = bv.get("sync-interval-secs")
        bv.set("sync-interval-secs", str(min(float(saved) * 4, 3600.0)))
        return saved

    def revert(self, garage, saved: Any) -> None:
        garage.bg_vars.set("sync-interval-secs", saved)


class _ScrubPause(_Step):
    name = "scrub-pause"

    def apply(self, garage) -> Any:
        sw = getattr(garage.block_manager, "scrub_worker", None)
        if sw is None:
            return None  # scrub disabled: the rung is a no-op
        saved = sw.paused
        sw.cmd_pause()
        return saved

    def revert(self, garage, saved: Any) -> None:
        sw = getattr(garage.block_manager, "scrub_worker", None)
        if sw is not None and saved is False:
            sw.cmd_resume()


class _ShedTier(_Step):
    def __init__(self, name: str, tier: int):
        self.name = name
        self.tier = tier

    def apply(self, garage) -> Any:
        ctl = garage.overload
        saved = ctl.shed_from_tier
        ctl.set_shed_tier(self.tier)
        return saved

    def revert(self, garage, saved: Any) -> None:
        garage.overload.set_shed_tier(saved)


def build_ladder() -> list[_Step]:
    from ..api.overload import TIER_ANON, TIER_LIST, TIER_WRITE

    return [
        _RepairSlow(),
        _SyncStretch(),
        _ScrubPause(),
        _ShedTier("shed-anonymous", TIER_ANON),
        _ShedTier("shed-list", TIER_LIST),
        _ShedTier("shed-write", TIER_WRITE),
    ]


# --- controller ---------------------------------------------------------------


class SheddingController(Worker):
    """Spawned by `Garage.spawn_workers()` when `[overload] enabled`.
    `evaluate()` is synchronous and clock-injected so the hysteresis
    state machine unit-tests without a running cluster."""

    def __init__(self, garage, clock=time.monotonic):
        self.garage = garage
        self.cfg = garage.config.overload
        self.clock = clock
        self.ladder = build_ladder()
        self.level = 0
        self._saved: list[Any] = []  # saved state per applied step
        self._recovered_since: float | None = None
        self.steps_up = 0
        self.steps_down = 0
        self.last_change: float | None = None
        self.last_reason: str | None = None
        self._last_blocked: float | None = None

    def name(self) -> str:
        return "shedding"

    def status(self) -> dict[str, Any]:
        return {
            "level": self.level,
            "steps": [s.name for s in self.ladder[: self.level]],
        }

    # --- signals --------------------------------------------------------------

    def signals(self, consume: bool = True) -> tuple[float, float]:
        """(max SLO burn rate, event-loop lag p99 seconds) — burn from
        the SloTracker, lag from the LOCAL telemetry digest (the same
        row this node gossips, rpc/telemetry_digest.py).

        Two guards keep quiet nodes off the ladder:
          - burn only counts once the SLO window holds at least
            `min_window_requests` (one 500 among a handful of requests
            is noise, not overload);
          - the lag histogram is CUMULATIVE, so its p99 remembers every
            stall the process ever had — the lag signal only counts
            while `event_loop_blocked_total` is still increasing, i.e.
            there is fresh stall evidence this interval.

        `consume=False` (status surfaces) leaves the stall-evidence
        edge detector untouched: a dashboard polling /v1/overload must
        not eat the `blocked`-increased evidence the controller's own
        next evaluate() needs."""
        slo = self.garage.slo_tracker.compute()
        minreq = int(self.cfg.min_window_requests)
        burn = 0.0
        for kind in ("availability", "latency_p99"):
            st = slo[kind]
            if st["window_total"] >= minreq:
                burn = max(burn, st["burn_rate"])
        dig = self.garage.telemetry.collect()
        loop_d = dig.get("loop") or {}
        lag = float(loop_d.get("p99") or 0.0)
        blocked = float(loop_d.get("blocked") or 0.0)
        fresh_stalls = (
            self._last_blocked is not None and blocked > self._last_blocked
        )
        if consume:
            self._last_blocked = blocked
        return burn, (lag if fresh_stalls else 0.0)

    # --- hysteresis state machine ---------------------------------------------

    def evaluate(self, now: float | None = None) -> None:
        """One control decision.  Separated from work() so tests drive
        it with a fake clock and injected signals."""
        if now is None:
            now = self.clock()
        cfg = self.cfg
        burn, lag = self.signals()
        lag_limit = float(cfg.loop_lag_p99_msec) / 1000.0
        overloaded = burn > float(cfg.ladder_burn_up) or lag > lag_limit
        recovered = (
            burn < float(cfg.ladder_burn_down) and lag < 0.5 * lag_limit
        )
        if overloaded:
            self._recovered_since = None
            if self.level < len(self.ladder):
                self._step_up(now, burn, lag)
        elif recovered and self.level > 0:
            if self._recovered_since is None:
                self._recovered_since = now
            elif now - self._recovered_since >= float(cfg.ladder_hold_secs):
                self._step_down(now, burn, lag)
                # hold again before the next step down: recovery is
                # re-proven at each level, so a marginal node descends
                # slowly instead of oscillating
                self._recovered_since = now
        else:
            # gray zone (or healthy at level 0): hold position
            self._recovered_since = None if not recovered else self._recovered_since

    def _step_up(self, now: float, burn: float, lag: float) -> None:
        step = self.ladder[self.level]
        try:
            self._saved.append(step.apply(self.garage))
        except Exception as e:  # noqa: BLE001 — a dead actuator must not
            # wedge the ladder; the rung applies as a no-op and the
            # controller keeps climbing if pressure persists
            logger.warning("ladder step %s failed to apply: %r", step.name, e)
            self._saved.append(None)
        self.level += 1
        self.steps_up += 1
        self.last_change = now
        self.last_reason = (
            f"burn={burn:.2f} lag_p99={lag * 1000:.0f}ms -> {step.name}"
        )
        registry.incr("overload_ladder_steps_total", (("direction", "up"),))
        logger.warning(
            "overload ladder UP to level %d (%s): %s",
            self.level, step.name, self.last_reason,
        )

    def _step_down(self, now: float, burn: float, lag: float) -> None:
        self.level -= 1
        step = self.ladder[self.level]
        saved = self._saved.pop()
        try:
            step.revert(self.garage, saved)
        except Exception as e:  # noqa: BLE001 — log and keep descending
            logger.warning("ladder step %s failed to revert: %r", step.name, e)
        self.steps_down += 1
        self.last_change = now
        self.last_reason = (
            f"burn={burn:.2f} lag_p99={lag * 1000:.0f}ms -> recover {step.name}"
        )
        registry.incr("overload_ladder_steps_total", (("direction", "down"),))
        logger.info(
            "overload ladder DOWN to level %d (recovered %s): %s",
            self.level, step.name, self.last_reason,
        )

    # --- worker ---------------------------------------------------------------

    async def work(self):
        self.evaluate()
        return (WorkerState.THROTTLED, float(self.cfg.check_interval_secs))

    def status_full(self) -> dict[str, Any]:
        """Ladder half of admin `GET /v1/overload`."""
        burn, lag = self.signals(consume=False)
        return {
            "level": self.level,
            "maxLevel": len(self.ladder),
            "ladder": [
                {"name": s.name, "applied": i < self.level}
                for i, s in enumerate(self.ladder)
            ],
            "burnRate": round(burn, 4),
            "loopLagP99Ms": round(lag * 1000.0, 2),
            "stepsUp": self.steps_up,
            "stepsDown": self.steps_down,
            "lastChangeAgoSecs": (
                round(self.clock() - self.last_change, 2)
                if self.last_change is not None
                else None
            ),
            "lastReason": self.last_reason,
            "thresholds": {
                "burnUp": self.cfg.ladder_burn_up,
                "burnDown": self.cfg.ladder_burn_down,
                "loopLagP99Msec": self.cfg.loop_lag_p99_msec,
                "holdSecs": self.cfg.ladder_hold_secs,
                "checkIntervalSecs": self.cfg.check_interval_secs,
            },
        }
