"""Cluster telemetry plane: gossiped node digests + federated rollup.

PRs 2-3 made every *node* deeply observable; this module makes the
*cluster* observable from any single node.  Monarch-style [Adya et al.,
VLDB'20], each node pre-aggregates a compact versioned digest of its own
registries (S3 RED numbers, resync/repair backlog, event-loop lag,
worker errors, breaker states, TPU dispatch rate, uptime) and piggybacks
it on the existing anti-entropy `NodeStatus` exchange (`rpc/system.py`)
— no new gossip round, no scrape fan-out, tolerant of old peers that
don't send the field.  Any node can then answer for the whole cluster:

  - `rollup(garage)`         JSON rollup: per-node rows + aggregates +
                             outliers + SLO state (admin
                             `GET /v1/cluster/telemetry`, `cluster top`)
  - `render_cluster_metrics` federated Prometheus exposition of the
                             digest families with a `node` label
                             (admin `GET /metrics/cluster`)
  - `detect_outliers`        median-absolute-deviation flags for nodes
                             whose latency / error rate / loop lag
                             deviate from the cluster (also surfaced in
                             `ClusterHealth.outlier_nodes`)
  - `SloTracker`             `[admin] slo_*` availability + p99-latency
                             targets -> `slo_error_budget_remaining` /
                             `slo_burn_rate` gauges

Digest rows are rendered inline from the live gossip state (never
registered as per-node registry gauges), so an expired/departed node
disappears from the rollup the moment `rpc/system.py` ages its status
entry out — there is no stale-gauge unregistration to forget.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from statistics import median
from typing import Any

from ..utils import metrics as metrics_mod

logger = logging.getLogger("garage.telemetry")

DIGEST_VERSION = 1

# Outlier detection: per-metric (digest key path, MAD floor, absolute
# minimum).  One-sided — only deviating HIGH is sick.  The MAD floor
# keeps a tight cluster (MAD ~ 0) from flagging noise-level deviations;
# the absolute minimum keeps a healthy-but-not-identical node (p99 of
# 8 ms vs the cluster's 2 ms) from ever being flagged.
MAD_K = 3.5  # modified z-score cutoff (Iglewicz & Hoaglin's suggestion)
OUTLIER_METRICS: list[tuple[str, str, float, float]] = [
    ("s3_p99_seconds", "s3 p99 latency", 0.010, 0.050),
    ("s3_error_fraction", "s3 error rate", 0.010, 0.050),
    ("loop_lag_p99_seconds", "event-loop lag p99", 0.010, 0.050),
]


def _s3_5xx_total(registry) -> float:
    """Cumulative S3 5xx count — the ONE definition of what burns the
    availability budget, shared by the digest collector and the SLO
    tracker so the gossiped error rate and the budget can't diverge."""
    return registry.counter_family_sum(
        "api_s3_error_counter",
        lambda labels: any(
            k == "code" and v.startswith("5") for k, v in labels
        ),
    )


def _finite(v: float | None) -> float | None:
    """Clamp a histogram quantile to the largest finite bucket bound:
    family_quantile returns inf when the quantile lands in the overflow
    bucket, and an inf in the digest would serialize as the RFC-invalid
    JSON token `Infinity` on the admin endpoints."""
    if v is None:
        return None
    return min(v, metrics_mod.BUCKETS[-1])


class DigestCollector:
    """Assembles this node's telemetry digest from the live registries.

    Counter-derived rates (req/s, err/s, dispatches/s) are deltas over
    the interval since the previous collection; collections are cached
    for `min_interval` so the admin endpoints re-reading the local row
    don't shrink the rate window to nothing.  `registry` is injectable:
    production uses the process-global one, tests give each in-process
    node its own (several Garage instances share a process there).
    """

    min_interval = 1.0
    # counter rates are deltas over a FIXED window, not "since whenever
    # collect() last ran": admin endpoints and health() also trigger
    # collections, and advancing the baseline on each of those would
    # make the gossiped req/s depend on the scrape frequency (a burst
    # 4 s before a scrape-triggered collect would gossip rps=0)
    rate_window = 10.0

    def __init__(self, garage, registry=None, clock=time.monotonic,
                 observatory=None, tenant_observatory=None):
        self.garage = garage
        self.registry = registry if registry is not None else metrics_mod.registry
        # traffic observatory (rpc/traffic.py): injectable for the same
        # reason the registry is — the production singleton is process-
        # wide, and in-process multi-node tests want per-node numbers
        self.observatory = observatory
        # tenant observatory (rpc/tenant.py): same injection contract
        self.tenant_observatory = tenant_observatory
        self.clock = clock
        self.started_at = clock()
        self._prev: dict[str, float] | None = None
        self._prev_t: float | None = None
        self._rates: dict[str, float] | None = None
        self._cached: dict[str, Any] | None = None
        self._cached_t = 0.0

    # --- counter snapshot ----------------------------------------------------

    def _obs(self):
        if self.observatory is not None:
            return self.observatory
        from .traffic import observatory

        return observatory

    def _tobs(self):
        if self.tenant_observatory is not None:
            return self.tenant_observatory
        from .tenant import observatory

        return observatory

    def _counters(self) -> dict[str, float]:
        r = self.registry
        return {
            "s3_req": r.counter_family_sum("api_s3_request_counter"),
            "s3_err": _s3_5xx_total(r),
            "tpu_disp": r.counter_family_sum("tpu_codec_dispatch_total"),
            # traffic-observatory op total: rides the same windowed-rate
            # machinery so the gossiped trf.rps can't drift from s3.rps
            # methodology
            "trf_ops": float(self._obs().total_ops),
            # tenant-observatory op total: same windowed-rate machinery,
            # so the gossiped tn.rps shares the s3.rps methodology
            "tn_ops": float(self._tobs().total_ops),
        }

    def collect(self) -> dict[str, Any]:
        """The digest as a compact msgpack-friendly dict (documented in
        doc/monitoring.md "Digest field catalogue")."""
        now = self.clock()
        if self._cached is not None and now - self._cached_t < self.min_interval:
            return self._cached
        g = self.garage
        r = self.registry
        cur = self._counters()
        if self._prev is None:
            self._prev, self._prev_t = cur, now
        elif now - self._prev_t >= self.rate_window:
            dt = now - self._prev_t
            self._rates = {
                k: max(0.0, cur[k] - self._prev[k]) / dt for k in cur
            }
            self._prev, self._prev_t = cur, now
        rates = self._rates if self._rates is not None else dict.fromkeys(cur, 0.0)

        breakers = {"open": 0, "half-open": 0, "sick": 0}
        ph = getattr(g, "peer_health", None)
        if ph is not None:
            for node in list(ph.peers):
                st = ph.state_of(node)
                if st in ("open", "half-open"):
                    breakers[st] += 1
                if ph.is_sick(node):
                    breakers["sick"] += 1

        planner = getattr(g, "repair_planner", None)
        planner_live = planner is not None and not planner.finished
        repair_backlog = (
            # the ledger lives on the checkpointable plan state;
            # queue_length() is the planner's own backlog accessor
            planner.queue_length() or 0 if planner_live else 0
        )
        # urgency breakdown (block/repair_plan.py classify buckets): the
        # total backlog alone can't tell "10k low-urgency stripes" from
        # "10k one-failure-from-loss stripes" — the distinction the
        # durability observatory and `cluster top` triage on
        urg = (
            planner.backlog_by_urgency()
            if planner_live
            else {"critical": 0, "high": 0, "low": 0, "lost": 0}
        )
        resync_age = g.block_manager.resync.oldest_error_age_secs()

        from ..ops.telemetry import codec_snapshot, platforms_seen

        # codec X-ray (ops/telemetry.py): dispatch pad-waste, compile
        # accounting, host<->device overlap, batcher lane linger — the
        # same snapshot the admin /v1/codec endpoint serves, reduced to
        # its scalar summary for gossip
        cx = codec_snapshot(r)
        digest: dict[str, Any] = {
            "v": DIGEST_VERSION,
            "up": round(now - self.started_at, 3),
            "s3": {
                "rps": round(rates["s3_req"], 4),
                "eps": round(rates["s3_err"], 4),
                "req": cur["s3_req"],
                "err": cur["s3_err"],
                "p50": _finite(r.family_quantile("api_s3_request_duration", 0.5)),
                "p99": _finite(r.family_quantile("api_s3_request_duration", 0.99)),
            },
            "loop": {
                "p99": _finite(r.family_quantile("event_loop_lag_seconds", 0.99)),
                "blocked": r.counter_family_sum("event_loop_blocked_total"),
            },
            "work": {
                "errs": r.gauge_family_sum("worker_errors_total"),
            },
            "resync": {
                "q": g.block_manager.resync.queue_len(),
                "err": g.block_manager.resync.errors_len(),
                # oldest error AGE (secs): transient blip vs stuck block
                "age": round(resync_age, 1) if resync_age is not None else None,
            },
            "repair": {
                "backlog": repair_backlog,
                "cr": urg.get("critical", 0),
                "hi": urg.get("high", 0),
                "lo": urg.get("low", 0),
                "lost": urg.get("lost", 0),
            },
            "rpc": breakers,
            "tpu": {
                "dps": round(rates["tpu_disp"], 4),
                "plat": ",".join(platforms_seen()) or None,
            },
            # codec X-ray summary (ISSUE 17) — "codec" keys are additive,
            # DIGEST_VERSION stays 1
            "codec": {
                "dsp": cx["dispatches"],
                "pw": cx["padWaste"],
                "ce": cx["compileEvents"],
                "cs": cx["compileSecs"],
                "ovl": cx["overlapEfficiency"],
                "ll99": cx["laneLingerP99"],
            },
        }
        # canary prober health (api/s3/canary.py): cumulative probes,
        # failures, probe p99 — all-zero on nodes without a prober, so
        # `cluster top` can tell "no canary" from "canary failing"
        from ..api.s3.canary import digest_fields as _canary_fields

        cn = _canary_fields(r)
        cn["p99"] = _finite(cn["p99"])
        # last-cycle verdict from the live worker (1 ok / 0 failing /
        # absent before the first cycle or without a prober): the
        # cumulative `err` count flags a node forever after one transient
        # blip — recency is what `cluster top`'s CANARY-FAIL keys off
        w = getattr(g, "canary", None)
        if w is not None and w.healthy is not None:
            cn["ok"] = w.healthy
        digest["canary"] = cn
        slo = getattr(g, "slo_tracker", None)
        if slo is not None:
            digest["slo"] = slo.digest_fields()
        # traffic observatory (rpc/traffic.py): op mix, hot bucket,
        # keyspace skew — "trf" keys are additive, DIGEST_VERSION stays 1
        digest["trf"] = self._obs().digest_fields(
            rates.get("trf_ops", 0.0)
        )
        # overload-control plane (api/overload.py + rpc/shedding.py):
        # ladder level + admission totals — a shedding node is visible
        # cluster-wide ("ovl" keys are additive, DIGEST_VERSION stays 1)
        ov = getattr(g, "overload", None)
        if ov is not None:
            ovl = ov.digest_fields()
            sh = getattr(g, "shedder", None)
            ovl["lvl"] = sh.level if sh is not None else 0
            digest["ovl"] = ovl
        # durability observatory (block/durability.py): redundancy-class
        # counts, min margin, repair ETA, zone exposure, layout-sync
        # progress — "dur" keys are additive, DIGEST_VERSION stays 1.
        # Counts are OWNED blocks, so the rollup's sums are exact.
        ds = getattr(g, "durability_scanner", None)
        if ds is not None:
            digest["dur"] = ds.digest_fields()
        # metadata plane (ISSUE 15): EFFECTIVE meta replication factor +
        # quorum sizes of the sharded tables, so a misconfigured meta RF
        # on any node is visible from every node ("meta" keys are
        # additive, DIGEST_VERSION stays 1).  Read from the live table
        # replication (not the config) so layout-driven fallback shows.
        rep = getattr(getattr(g, "object_table", None), "replication", None)
        if rep is not None and hasattr(rep, "effective_rf"):
            digest["meta"] = {
                "rf": int(rep.effective_rf()),
                "rq": int(rep.read_quorum()),
                "wq": int(rep.write_quorum()),
            }
        # rebalance observatory (rpc/transition.py): this node's layout
        # version / ack / sync trackers, transition progress and clock
        # skew — "lt" keys are additive, DIGEST_VERSION stays 1.  The
        # gossiped ack/sync versions are what let ANY node compute the
        # cluster's version spread and per-node staleness.
        tt = getattr(g, "transition_tracker", None)
        if tt is not None:
            digest["lt"] = tt.digest_fields()
        # tenant observatory (rpc/tenant.py): bounded top-N per-tenant
        # rows + node scalars — "tn" keys are additive, DIGEST_VERSION
        # stays 1.  Tenant key ids ride the JSON digest only; the
        # federated exposition renders just the numeric scalars.
        digest["tn"] = self._tobs().digest_fields(rates.get("tn_ops", 0.0))
        self._cached, self._cached_t = digest, now
        return digest


# --- SLO tracker --------------------------------------------------------------


class SloTracker:
    """Error-budget accounting for the S3 frontend against the `[admin]`
    `slo_availability_target` (percent of requests answered without a
    5xx) and `slo_latency_p99_target_msec` (percent of requests under
    the latency target — same availability percentage applies) over a
    rolling `slo_window_secs` window.

    compute() compares the oldest in-window snapshot of the cumulative
    counters with now, so the scrape rate doesn't change the math.
    Gauges (registered by model/garage.py):

      slo_error_budget_remaining{slo="availability"|"latency_p99"}
          1.0 = untouched budget, 0.0 = spent, negative = blown
      slo_burn_rate{slo=...}
          bad-fraction / allowed-fraction over the window; sustained
          > 1.0 means the budget will not survive the window
    """

    def __init__(self, registry=None, *, availability_target=99.9,
                 latency_target_msec=1000.0, window_secs=3600.0,
                 clock=time.monotonic):
        self.registry = registry if registry is not None else metrics_mod.registry
        self.target = min(float(availability_target), 100.0) / 100.0
        self.latency_target = float(latency_target_msec) / 1000.0
        self.window = float(window_secs)
        self.clock = clock
        # (t, requests, 5xx errors, latency-observed, latency-over)
        self._snaps: deque[tuple[float, float, float, int, int]] = deque()
        self._computed: tuple[float, dict] | None = None

    def _snapshot(self) -> tuple[float, float, float, int, int]:
        r = self.registry
        req = r.counter_family_sum("api_s3_request_counter")
        err = _s3_5xx_total(r)
        lat_n, lat_over = r.family_count_over(
            "api_s3_request_duration", self.latency_target
        )
        now = self.clock()
        snap = (now, req, err, lat_n, lat_over)
        # coalesce bursts (one /metrics scrape evaluates 4 SLO gauges =
        # 4 compute() calls): replace a sub-200ms-old tail instead of
        # appending, keeping the newest snapshot current while bounding
        # the deque; never replace the window's oldest entry
        if len(self._snaps) > 1 and now - self._snaps[-1][0] < 0.2:
            self._snaps[-1] = snap
        else:
            self._snaps.append(snap)
        while self._snaps and now - self._snaps[0][0] > self.window:
            self._snaps.popleft()
        return self._snaps[0]

    def compute(self) -> dict[str, dict[str, float]]:
        # one /metrics scrape evaluates four SLO gauge fns; a brief
        # result cache makes that one snapshot + one histogram merge
        now = self.clock()
        if self._computed is not None and now - self._computed[0] < 0.1:
            return self._computed[1]
        first = self._snapshot()
        last = self._snaps[-1]
        allowed = max(1.0 - self.target, 1e-9)

        def budget(total: float, bad: float) -> dict[str, float]:
            if total <= 0:
                return {"bad_fraction": 0.0, "burn_rate": 0.0,
                        "budget_remaining": 1.0, "window_total": 0.0,
                        "window_bad": 0.0}
            frac = bad / total
            return {
                "bad_fraction": frac,
                "burn_rate": frac / allowed,
                "budget_remaining": 1.0 - frac / allowed,
                "window_total": total,
                "window_bad": bad,
            }

        result = {
            "availability": budget(last[1] - first[1], last[2] - first[2]),
            "latency_p99": budget(last[3] - first[3], last[4] - first[4]),
        }
        self._computed = (now, result)
        return result

    def digest_fields(self) -> dict[str, Any]:
        c = self.compute()
        return {
            "target": round(self.target, 6),
            "lat_target": self.latency_target,
            "avail": {
                "rem": round(c["availability"]["budget_remaining"], 4),
                "burn": round(c["availability"]["burn_rate"], 4),
                "n": c["availability"]["window_total"],
                "bad": c["availability"]["window_bad"],
            },
            "lat": {
                "rem": round(c["latency_p99"]["budget_remaining"], 4),
                "burn": round(c["latency_p99"]["burn_rate"], 4),
                "n": c["latency_p99"]["window_total"],
                "bad": c["latency_p99"]["window_bad"],
            },
        }


# --- rollup -------------------------------------------------------------------


def _valid_digest(obj: Any) -> dict[str, Any] | None:
    """Gate a gossiped digest: only a dict stamped with OUR schema
    version is consumed.  A newer peer's v2 digest (rolling upgrade) or
    a malformed one degrades that node to a digest-less row — the
    federated endpoint must keep serving the rest of the cluster, not
    500 on float(<unexpected type>)."""
    if isinstance(obj, dict) and obj.get("v") == DIGEST_VERSION:
        return obj
    return None


def _node_rows(system) -> list[dict[str, Any]]:
    """Per-node rows: self (fresh local digest) + every unexpired
    node_status entry (digest may be None for old- or newer-version
    peers)."""
    system.expire_node_status()
    st = system.local_status()
    rows = [
        {
            "id": system.id.hex(),
            "hostname": st.hostname,
            "isSelf": True,
            "isUp": True,
            "ageSecs": 0.0,
            "metaDiskAvail": st.meta_disk_avail,
            "dataDiskAvail": st.data_disk_avail,
            "digest": _valid_digest(st.telemetry),
        }
    ]
    now = time.monotonic()
    for pid, (pst, ts) in sorted(system.node_status.items()):
        rows.append(
            {
                "id": pid.hex(),
                "hostname": pst.hostname,
                "isSelf": False,
                "isUp": system.netapp.is_connected(pid),
                "ageSecs": round(max(0.0, now - ts), 3),
                "metaDiskAvail": pst.meta_disk_avail,
                "dataDiskAvail": pst.data_disk_avail,
                "digest": _valid_digest(pst.telemetry),
            }
        )
    return rows


def _dig(row: dict, *path, default=None):
    cur = row.get("digest")
    for p in path:
        if not isinstance(cur, dict):
            return default
        cur = cur.get(p)
    return cur if cur is not None else default


def _metric_values(rows) -> dict[str, dict[str, float]]:
    """node id -> value per outlier metric (nodes without the datum are
    skipped for that metric, not defaulted — an old peer must not drag
    the median)."""
    out: dict[str, dict[str, float]] = {k: {} for k, *_ in OUTLIER_METRICS}
    for row in rows:
        if row.get("digest") is None:
            continue
        nid = row["id"]
        try:
            p99 = _dig(row, "s3", "p99")
            if p99 is not None:
                out["s3_p99_seconds"][nid] = float(p99)
            rps = _dig(row, "s3", "rps", default=0.0)
            eps = _dig(row, "s3", "eps", default=0.0)
            if rps or eps:
                # rps already includes errored requests (the request
                # counter increments before the handler runs), so the
                # error fraction is eps/rps — an all-5xx node must
                # score 1.0, not 0.5.  Noise floor: below ~3 errors per
                # rate window (0.3/s over 10 s) the fraction reads 0 —
                # one transient 500 in a low-traffic window must not
                # MAD-flag a node (healthy nodes stay in the population
                # at 0 so the detector keeps its median)
                out["s3_error_fraction"][nid] = (
                    min(1.0, float(eps) / max(float(rps), 1e-9))
                    if float(eps) >= 0.3
                    else 0.0
                )
            lag = _dig(row, "loop", "p99")
            if lag is not None:
                out["loop_lag_p99_seconds"][nid] = float(lag)
        except (TypeError, ValueError):
            # malformed values: skip the node, don't drag the median
            for per_node in out.values():
                per_node.pop(nid, None)
    return out


def detect_outliers(rows) -> dict[str, list[str]]:
    """node id -> reasons, via one-sided modified z-score (MAD) per
    metric.  Needs >= 3 nodes reporting a metric to say anything."""
    flagged: dict[str, list[str]] = {}
    values = _metric_values(rows)
    for key, label, mad_floor, abs_min in OUTLIER_METRICS:
        per_node = values[key]
        if len(per_node) < 3:
            continue
        med = median(per_node.values())
        mad = median(abs(v - med) for v in per_node.values())
        scale = max(1.4826 * mad, mad_floor)
        for nid, v in per_node.items():
            if v < abs_min:
                continue
            score = (v - med) / scale
            if score > MAD_K:
                flagged.setdefault(nid, []).append(
                    f"{label} {v:.3g} vs cluster median {med:.3g}"
                )
    return flagged


def outlier_node_ids(system) -> list[str]:
    """The outlier set alone (ClusterHealth.outlier_nodes feed).  Built
    from digests only — health() is called on every /metrics scrape,
    /v1/status and status CLI, and the full _node_rows pass would run
    local_status()'s two blocking disk_usage syscalls each time just to
    count outliers.  Digest collection itself is cached (~1 s)."""
    try:
        system.expire_node_status()
        rows: list[dict[str, Any]] = []
        if system.telemetry_collector is not None:
            rows.append(
                {
                    "id": system.id.hex(),
                    "digest": _valid_digest(system.telemetry_collector()),
                }
            )
        for pid, (pst, _ts) in system.node_status.items():
            rows.append(
                {"id": pid.hex(), "digest": _valid_digest(pst.telemetry)}
            )
        return sorted(detect_outliers(rows))
    except Exception as e:  # noqa: BLE001 — health() must never fail on telemetry
        logger.debug("outlier computation failed: %r", e)
        return []


def _num(v, default: float | None = None) -> float | None:
    """Tolerant numeric coercion: _valid_digest only gates the schema
    VERSION, so a buggy v1 peer can still put a string/dict where a
    number belongs — the aggregate paths must degrade, not 500."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def _dsum(rows, *path) -> float:
    return sum(
        _num(_dig(r, *path, default=0.0), default=0.0) for r in rows
    )


def _tenant_hog_share(with_digest) -> tuple[float | None, int]:
    """`(cluster-wide top-1 tenant ops share, distinct tenants seen)`
    from the gossiped `tn.rows` sections (share is None until some node
    reports a tenant).  Summing the per-node rows BEFORE taking the max
    is the whole point: a tenant spread thin over 11 frontends looks
    modest on every node row yet tops the cluster table — this is the
    number the `cluster top` hog column and the HOG! flag key off."""
    totals: dict[str, float] = {}
    for r in with_digest:
        trows = _dig(r, "tn", "rows")
        if not isinstance(trows, list):
            continue
        for t in trows:
            if not isinstance(t, dict) or not isinstance(t.get("id"), str):
                continue
            totals[t["id"]] = totals.get(t["id"], 0.0) + (
                _num(t.get("ops"), 0.0) or 0.0
            )
    total = sum(totals.values())
    if not totals or total <= 0:
        return None, len(totals)
    return max(totals.values()) / total, len(totals)


def _cluster_slo(garage, with_digest) -> dict[str, Any] | None:
    """Request-weighted cluster SLO across every reporting node's
    window — shared by rollup() and the federated exposition (which must
    not pay for the full rollup, health scan included, per scrape)."""
    tr = getattr(garage, "slo_tracker", None)
    if tr is None:
        return None
    allowed = max(1.0 - tr.target, 1e-9)

    def agg(kind: str) -> dict[str, float]:
        total = _dsum(with_digest, "slo", kind, "n")
        bad = _dsum(with_digest, "slo", kind, "bad")
        frac = bad / total if total > 0 else 0.0
        return {
            "windowTotal": total,
            "windowBad": bad,
            "burnRate": frac / allowed,
            "budgetRemaining": 1.0 - frac / allowed,
        }

    return {
        "availabilityTarget": tr.target,
        "latencyP99TargetSecs": tr.latency_target,
        "windowSecs": tr.window,
        "availability": agg("avail"),
        "latencyP99": agg("lat"),
    }


def rollup(garage, rows=None, outliers=None) -> dict[str, Any]:
    """The one-stop cluster JSON (admin GET /v1/cluster/telemetry).
    `rows`/`outliers`: precomputed by a caller that already built them —
    each _node_rows pass costs two blocking disk_usage syscalls on the
    event loop, so don't repeat it."""
    if rows is None:
        rows = _node_rows(garage.system)
    if outliers is None:
        outliers = detect_outliers(rows)
    with_digest = [r for r in rows if r.get("digest") is not None]

    def dsum(*path) -> float:
        return _dsum(with_digest, *path)

    def dmax(*path) -> float | None:
        vals = [
            v
            for r in with_digest
            if (v := _num(_dig(r, *path))) is not None
        ]
        return max(vals) if vals else None

    def dmin(*path) -> float | None:
        vals = [
            v
            for r in with_digest
            if (v := _num(_dig(r, *path))) is not None
        ]
        return min(vals) if vals else None

    slo = _cluster_slo(garage, with_digest)
    hog_share, tenants_seen = _tenant_hog_share(with_digest)
    h = garage.system.health(outlier_nodes=sorted(outliers))
    return {
        "node": garage.node_id.hex(),
        "clusterHealth": h.__dict__,
        "nodes": rows,
        "nodesReporting": len(with_digest),
        "aggregate": {
            "s3RequestsPerSec": round(dsum("s3", "rps"), 4),
            "s3ErrorsPerSec": round(dsum("s3", "eps"), 4),
            "s3P99SecondsWorst": dmax("s3", "p99"),
            "loopLagP99SecondsWorst": dmax("loop", "p99"),
            "resyncQueue": dsum("resync", "q"),
            "resyncErrors": dsum("resync", "err"),
            "repairBacklog": dsum("repair", "backlog"),
            "workerErrors": dsum("work", "errs"),
            "breakersOpen": dsum("rpc", "open"),
            "tpuDispatchPerSec": round(dsum("tpu", "dps"), 4),
            # codec X-ray: dispatches sum exactly (per-node cumulative
            # counters); pad-waste and overlap are worst-over-nodes (the
            # triage question is "is ANY node wasting its accelerator"),
            # compile events/seconds sum (cluster-wide recompile burden)
            "codecDispatches": dsum("codec", "dsp"),
            "codecPadWasteWorst": dmax("codec", "pw"),
            "codecCompileEvents": dsum("codec", "ce"),
            "codecCompileSeconds": round(dsum("codec", "cs"), 4),
            "codecOverlapEfficiencyWorst": dmax("codec", "ovl"),
            "codecLaneLingerP99SecondsWorst": dmax("codec", "ll99"),
            # durability observatory: per-node counts are OWNED blocks,
            # so sums are exact cluster totals; min-redundancy is the
            # min over nodes (distance from data loss), ETA the max
            # (the slowest node gates full redundancy)
            "durabilityHealthy": dsum("dur", "h"),
            "durabilityDegraded": dsum("dur", "dg"),
            "durabilityAtRisk": dsum("dur", "ar"),
            "durabilityUnreadable": dsum("dur", "ur"),
            "durabilityMinRedundancy": dmin("dur", "minr"),
            "repairEtaSecondsWorst": dmax("dur", "eta"),
            # nodes with missing pieces but NO eta (stalled/unmeasured):
            # dmax drops their None, so a healthy node's 0.0 would
            # otherwise mask a repair that isn't draining at all
            "repairEtaUnknownNodes": sum(
                1
                for r in with_digest
                if (_num(_dig(r, "dur", "mp"), 0.0) or 0.0) > 0
                and _num(_dig(r, "dur", "eta")) is None
            ),
            # rebalance observatory: version spread = newest layout
            # version anyone knows minus the oldest ack anyone reports
            # (0 = converged); worst |skew| bounds the merged event
            # timeline's ordering error
            "layoutVersionSpread": (
                (dmax("lt", "v") or 0) - (dmin("lt", "ack") or 0)
                if dmax("lt", "v") is not None
                and dmin("lt", "ack") is not None
                else 0
            ),
            "layoutNodesInTransition": sum(
                1
                for r in with_digest
                if (_num(_dig(r, "lt", "act"), 0.0) or 0.0) >= 2
            ),
            "clockSkewWorstMs": max(
                (
                    abs(v)
                    for r in with_digest
                    if (v := _num(_dig(r, "lt", "sk"))) is not None
                ),
                default=None,
            ),
            "clockSkewWarnMs": garage.config.admin.clock_skew_warn_msec,
            # tenant observatory: worst cluster-wide tenant ops share
            # (per-node tn.rows summed by tenant id first), distinct
            # tenants seen (fair share = 1/tenantsSeen), and the
            # fair-share-multiple knob the HOG! flag compares against
            "tenantHogShare": hog_share,
            "tenantsSeen": tenants_seen,
            "tenantHogShareWarn": garage.config.admin.tenant_hog_share,
        },
        "outliers": outliers,
        "slo": slo,
        # newest banked TPU probe wedge verdict (bench.py phased_probe,
        # ISSUE 11): per-box, so this is the ANSWERING node's probe
        # history — null on boxes whose probe never failed
        "tpuProbe": _probe_summary(),
    }


def _probe_summary():
    from ..ops.telemetry import probe_failure_summary

    return probe_failure_summary()


def codec_response(garage) -> dict:
    """The one serialization of the codec X-ray, shared by admin
    `GET /v1/codec`, the admin-RPC `codec` op and the `cluster codec` /
    `codec top` CLI (key casing cannot drift between transports).

    `local` is the full ops/telemetry.codec_snapshot — per-kernel pad
    accounting, per-cache compile events, per-lane linger — read from
    this node's own registry.  Cluster rows come from the gossiped
    `codec.*` digest keys, so any node answers for all; a digest-less
    old peer renders `codec: null`, never an error.  Rows are NOT
    filtered to connected peers: the fields are cumulative process
    counters, and a dead peer's last-known compile/pad numbers are
    still the right triage input (unlike durability, nothing here is
    re-owned on failure, so nothing double-counts)."""
    from ..ops.telemetry import codec_snapshot

    system = garage.system
    system.expire_node_status()
    local = _valid_digest(garage.telemetry.collect()) or {}
    rows = [
        {
            "id": system.id.hex(),
            "isSelf": True,
            "isUp": True,
            "codec": local.get("codec"),
        }
    ]
    for pid, (pst, _ts) in sorted(system.node_status.items()):
        d = _valid_digest(pst.telemetry) or {}
        rows.append(
            {
                "id": pid.hex(),
                "isSelf": False,
                "isUp": system.netapp.is_connected(pid),
                "codec": d.get("codec"),
            }
        )
    with_codec = [r for r in rows if isinstance(r.get("codec"), dict)]

    def nsum(key: str) -> float:
        return sum(_num(r["codec"].get(key), 0.0) or 0.0 for r in with_codec)

    def nmax(key: str) -> float | None:
        vals = [
            v
            for r in with_codec
            if (v := _num(r["codec"].get(key))) is not None
        ]
        return max(vals) if vals else None

    return {
        "node": garage.node_id.hex(),
        "local": codec_snapshot(garage.telemetry.registry),
        "cluster": {
            "nodes": rows,
            "nodesReporting": len(with_codec),
            "aggregate": {
                # sums are exact (cumulative per-process counters);
                # waste/overlap/linger take the worst node — the triage
                # question is "is ANY node wasting its accelerator"
                "dispatches": nsum("dsp"),
                "padWasteWorst": nmax("pw"),
                "compileEvents": nsum("ce"),
                "compileSeconds": round(nsum("cs"), 4),
                "overlapEfficiencyWorst": nmax("ovl"),
                "laneLingerP99SecondsWorst": nmax("ll99"),
            },
        },
    }


# --- federated exposition -----------------------------------------------------

# family -> (type, help, digest path or callable(row))
_CLUSTER_FAMILIES: list[tuple[str, str, Any]] = [
    ("cluster_node_up", "node connected from the answering node",
     lambda row: 1.0 if row["isUp"] else 0.0),
    ("cluster_node_status_age_seconds", "age of the node's last status",
     lambda row: row["ageSecs"]),
    ("cluster_node_uptime_seconds", "node uptime", ("up",)),
    ("cluster_node_s3_requests_per_second", "S3 request rate", ("s3", "rps")),
    ("cluster_node_s3_errors_per_second", "S3 5xx rate", ("s3", "eps")),
    ("cluster_node_s3_p50_seconds", "S3 latency p50", ("s3", "p50")),
    ("cluster_node_s3_p99_seconds", "S3 latency p99", ("s3", "p99")),
    ("cluster_node_event_loop_lag_p99_seconds", "event-loop lag p99",
     ("loop", "p99")),
    ("cluster_node_event_loop_blocked_total", "loop stall episodes",
     ("loop", "blocked")),
    ("cluster_node_worker_errors", "cumulative worker errors",
     ("work", "errs")),
    ("cluster_node_resync_queue_length", "resync backlog", ("resync", "q")),
    ("cluster_node_resync_errored_blocks", "resync error blocks",
     ("resync", "err")),
    ("cluster_node_resync_oldest_error_age_seconds",
     "age of the node's oldest resync error", ("resync", "age")),
    ("cluster_node_repair_backlog", "repair-plan ledger backlog",
     ("repair", "backlog")),
    ("cluster_node_repair_backlog_critical",
     "repair-plan stripes one failure from loss", ("repair", "cr")),
    ("cluster_node_breakers_open", "peers behind an open breaker",
     ("rpc", "open")),
    ("cluster_node_tpu_dispatch_per_second", "TPU codec dispatch rate",
     ("tpu", "dps")),
    ("cluster_node_canary_probes", "cumulative canary probe legs",
     ("canary", "ops")),
    ("cluster_node_canary_errors", "cumulative failed canary probe legs",
     ("canary", "err")),
    ("cluster_node_canary_p99_seconds", "canary probe latency p99",
     ("canary", "p99")),
    ("cluster_node_disk_avail_bytes", "free disk bytes (meta dir)",
     lambda row: (row.get("metaDiskAvail") or (None,))[0]),
    ("cluster_node_overload_ladder_level",
     "overload degradation-ladder level (0 = healthy)", ("ovl", "lvl")),
    ("cluster_node_shed_requests", "cumulative admission-shed requests",
     ("ovl", "shed")),
    ("cluster_node_in_flight_requests", "admitted requests in flight",
     ("ovl", "inf")),
    # traffic observatory (rpc/traffic.py): numeric trf digest fields
    # only — the hot bucket NAME stays in the JSON surfaces, never a
    # label (metrics-lint cardinality guard)
    ("cluster_node_traffic_ops_total",
     "cumulative observatory-recorded S3 ops", ("trf", "ops")),
    ("cluster_node_traffic_ops_per_second",
     "observatory op rate", ("trf", "rps")),
    ("cluster_node_traffic_read_fraction",
     "read share of object traffic (GET+HEAD over all object ops)",
     ("trf", "rdf")),
    ("cluster_node_traffic_bytes_total",
     "cumulative object payload bytes moved", ("trf", "by")),
    ("cluster_node_traffic_hot_bucket_ops_per_second",
     "approximate op rate of the node's hottest bucket", ("trf", "hbps")),
    ("cluster_node_traffic_zipf_skew",
     "estimated zipf exponent of the key popularity", ("trf", "zipf")),
    # durability observatory (block/durability.py): numeric dur digest
    # fields only — zone NAMES stay in /v1/cluster/durability JSON,
    # never a label (metrics-lint cardinality discipline)
    ("cluster_node_durability_blocks_total",
     "blocks owned and classified by the node's ledger", ("dur", "tot")),
    ("cluster_node_durability_blocks_healthy",
     "owned blocks with all k+m pieces on live ranks", ("dur", "h")),
    ("cluster_node_durability_blocks_degraded",
     "owned blocks with k < live pieces < k+m", ("dur", "dg")),
    ("cluster_node_durability_blocks_at_risk",
     "owned blocks one failure away from loss (live == k)",
     ("dur", "ar")),
    ("cluster_node_durability_blocks_unreadable",
     "owned blocks below k live pieces", ("dur", "ur")),
    ("cluster_node_durability_missing_pieces",
     "pieces missing across the node's owned blocks", ("dur", "mp")),
    ("cluster_node_durability_min_redundancy",
     "worst live-minus-k margin across owned blocks (min over nodes = "
     "the cluster's distance from data loss)", ("dur", "minr")),
    ("cluster_node_durability_repair_eta_seconds",
     "estimated seconds until the repair backlog drains", ("dur", "eta")),
    ("cluster_node_durability_backlog_bytes",
     "estimated bytes of missing redundancy", ("dur", "bkb")),
    ("cluster_node_durability_zone_exposed_blocks",
     "owned blocks a single worst-zone loss would drop below k",
     ("dur", "zx")),
    ("cluster_node_layout_sync_fraction",
     "fraction of partitions synced to the current layout version",
     ("dur", "lt")),
    # codec X-ray (ISSUE 17, ops/telemetry.py codec_snapshot): dispatch
    # pad-waste, compile accounting, transfer/compute overlap, batcher
    # lane linger — per-kernel breakdowns stay in /v1/codec JSON, only
    # node-level scalars federate
    ("cluster_node_codec_dispatch_total",
     "cumulative device codec dispatches", ("codec", "dsp")),
    ("cluster_node_codec_pad_waste",
     "fraction of dispatched rows that were bucket padding",
     ("codec", "pw")),
    ("cluster_node_codec_compile_events",
     "cumulative compile events (cache misses + first-shape lowerings)",
     ("codec", "ce")),
    ("cluster_node_codec_compile_seconds",
     "cumulative wall seconds spent compiling", ("codec", "cs")),
    ("cluster_node_codec_overlap_efficiency",
     "wall over transfer-plus-compute (1.0 = fully sequential phases)",
     ("codec", "ovl")),
    ("cluster_node_codec_lane_linger_p99_seconds",
     "batcher lane linger p99 (arrival to dispatch)", ("codec", "ll99")),
    # metadata plane (ISSUE 15): effective table replication factor +
    # quorum sizes — a node whose meta RF disagrees with the cluster
    # stands out on one federated scrape
    ("cluster_node_meta_replication_factor",
     "effective metadata-table replication factor", ("meta", "rf")),
    ("cluster_node_meta_read_quorum",
     "metadata-table read quorum", ("meta", "rq")),
    ("cluster_node_meta_write_quorum",
     "metadata-table write quorum", ("meta", "wq")),
    # rebalance observatory (rpc/transition.py): each node's layout
    # version / CRDT tracker positions + transition progress + the
    # NTP-style clock skew the federated event timeline depends on —
    # (src, dst) pair breakdowns stay in /v1/cluster/transition JSON
    # and the node-local `layout_transition_pair_bytes_total` counter
    ("cluster_node_layout_version",
     "newest layout version the node knows", ("lt", "v")),
    ("cluster_node_layout_ack_version",
     "layout version the node has acked (CRDT ack tracker)",
     ("lt", "ack")),
    ("cluster_node_layout_sync_version",
     "layout version the node has fully synced to (CRDT sync tracker)",
     ("lt", "sync")),
    ("cluster_node_layout_active_versions",
     "layout versions with a ring assignment (2+ = transition open)",
     ("lt", "act")),
    ("cluster_node_layout_transition_bytes_moved",
     "bytes moved by the node during the open layout transition",
     ("lt", "mvb")),
    ("cluster_node_layout_transition_throughput_bytes_per_second",
     "EWMA rebalance ingest throughput during the open transition",
     ("lt", "thr")),
    ("cluster_node_layout_transition_eta_seconds",
     "estimated seconds until the node sees sync fraction 1.0",
     ("lt", "eta")),
    ("cluster_node_clock_skew_ms",
     "median NTP-style wall-clock offset vs peers (positive = peers "
     "ahead); the merged event timeline's ordering error bound",
     ("lt", "sk")),
    # tenant observatory (rpc/tenant.py): numeric tn digest scalars
    # only — tenant key ids stay in /v1/cluster/tenants JSON, never a
    # label (the PR 12 cardinality rule)
    ("cluster_node_tenant_tracked",
     "distinct tenant keys the node's sketch currently tracks",
     ("tn", "trk")),
    ("cluster_node_tenant_ops_total",
     "cumulative tenant-attributed S3 ops", ("tn", "ops")),
    ("cluster_node_tenant_ops_per_second",
     "tenant-attributed op rate", ("tn", "rps")),
    ("cluster_node_tenant_sheds_total",
     "cumulative admission sheds joined to a claimed tenant",
     ("tn", "shed")),
    ("cluster_node_tenant_top1_share",
     "ops share of the node's busiest tenant", ("tn", "top1")),
    ("cluster_node_tenant_worst_burn",
     "worst per-tenant SLO burn rate on the node (availability or "
     "latency dimension)", ("tn", "wburn")),
    ("cluster_node_tenant_claimed_mismatches_total",
     "requests whose pre-auth claimed key id disagreed with the "
     "SigV4-authenticated id", ("tn", "mm")),
]


def render_cluster_metrics(garage) -> str:
    """Prometheus exposition of the cluster digest with a `node` label —
    one scrape of any node federates the whole cluster.  Passes the
    metrics-lint parser (one TYPE per family, before its samples, no
    duplicate (name, labels))."""
    rows = _node_rows(garage.system)
    outliers = detect_outliers(rows)
    lines: list[str] = []

    def lbl(row) -> str:
        return '{node="%s"}' % row["id"][:16]

    for fam, help_, src in _CLUSTER_FAMILIES:
        samples = []
        for row in rows:
            if callable(src):
                v = src(row)
            else:
                if row.get("digest") is None:
                    continue  # old peer without the field: no sample
                v = _dig(row, *src)
            if v is None:
                continue
            try:
                samples.append(f"{fam}{lbl(row)} {float(v):g}")
            except (TypeError, ValueError):
                continue  # one weird value must not 500 the endpoint
        if samples:
            lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} gauge")
            lines.extend(samples)

    lines.append("# HELP cluster_node_outlier MAD-flagged sick node")
    lines.append("# TYPE cluster_node_outlier gauge")
    for row in rows:
        lines.append(
            f"cluster_node_outlier{lbl(row)} "
            f"{1 if row['id'] in outliers else 0}"
        )
    lines.append("# TYPE cluster_outlier_nodes gauge")
    lines.append(f"cluster_outlier_nodes {len(outliers)}")
    lines.append("# TYPE cluster_nodes_reporting gauge")
    lines.append(
        "cluster_nodes_reporting "
        f"{sum(1 for r in rows if r.get('digest') is not None)}"
    )

    slo = _cluster_slo(
        garage, [r for r in rows if r.get("digest") is not None]
    )
    if slo is not None:
        lines.append("# TYPE cluster_slo_error_budget_remaining gauge")
        lines.append("# TYPE cluster_slo_burn_rate gauge")
        for kind, key in (("availability", "availability"),
                          ("latency_p99", "latencyP99")):
            s = slo[key]
            lines.append(
                f'cluster_slo_error_budget_remaining{{slo="{kind}"}} '
                f'{s["budgetRemaining"]:g}'
            )
            lines.append(
                f'cluster_slo_burn_rate{{slo="{kind}"}} {s["burnRate"]:g}'
            )
    return "\n".join(lines) + "\n"
