"""Replication factor, consistency mode and quorum arithmetic.

Reference src/rpc/replication_mode.rs:8-59:
  read_quorum  = ceil(rf/2)   (degraded/dangerous read 1)
  write_quorum = rf + 1 - read_quorum   (dangerous writes 1)
so read_quorum + write_quorum = rf + 1 > rf (read-your-writes).
RF=3 consistent => read 2 / write 2; RF=2 => read 1 / write 2.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ReplicationMode:
    replication_factor: int
    consistency_mode: str = "consistent"  # consistent | degraded | dangerous

    def __post_init__(self):
        if self.replication_factor < 1:
            raise ValueError("replication_factor must be >= 1")
        if self.consistency_mode not in ("consistent", "degraded", "dangerous"):
            raise ValueError(f"bad consistency mode {self.consistency_mode!r}")

    def read_quorum(self) -> int:
        if self.consistency_mode == "consistent":
            return (self.replication_factor + 1) // 2
        return 1  # degraded | dangerous

    def write_quorum(self) -> int:
        if self.consistency_mode == "dangerous":
            return 1
        return self.replication_factor + 1 - self.read_quorum()

    def is_read_after_write_consistent(self) -> bool:
        return self.read_quorum() + self.write_quorum() > self.replication_factor
