"""Replication factor, consistency mode and quorum arithmetic.

Reference src/rpc/replication_mode.rs:8-59:
  read_quorum  = ceil(rf/2)   (degraded/dangerous read 1)
  write_quorum = rf + 1 - read_quorum   (dangerous writes 1)
so read_quorum + write_quorum = rf + 1 > rf (read-your-writes).
RF=3 consistent => read 2 / write 2; RF=2 => read 1 / write 2.

ISSUE 15 splits the cluster into TWO quorum tuples: the block plane
keeps `replication_factor` (the EC stripe width k+m), while the
metadata tables carry their own smaller factor (`[meta]
replication_factor`, default 3) so table quorums are O(1) in stripe
width.  The module-level `read_quorum_for`/`write_quorum_for` are the
one implementation of the arithmetic — the meta ring computes its
quorums at the EFFECTIVE factor (min(meta_rf, layout rf), see
table/replication.py) and must not be able to drift from the block
plane's math.
"""

from __future__ import annotations

from dataclasses import dataclass

CONSISTENCY_MODES = ("consistent", "degraded", "dangerous")


def read_quorum_for(rf: int, consistency_mode: str = "consistent") -> int:
    """Read quorum at factor `rf` (ceil(rf/2) when consistent)."""
    if consistency_mode == "consistent":
        return (rf + 1) // 2
    return 1  # degraded | dangerous


def write_quorum_for(rf: int, consistency_mode: str = "consistent") -> int:
    if consistency_mode == "dangerous":
        return 1
    return rf + 1 - read_quorum_for(rf, consistency_mode)


@dataclass(frozen=True)
class ReplicationMode:
    replication_factor: int
    consistency_mode: str = "consistent"  # consistent | degraded | dangerous

    def __post_init__(self):
        if self.replication_factor < 1:
            raise ValueError("replication_factor must be >= 1")
        if self.consistency_mode not in CONSISTENCY_MODES:
            raise ValueError(f"bad consistency mode {self.consistency_mode!r}")

    def read_quorum(self) -> int:
        return read_quorum_for(self.replication_factor, self.consistency_mode)

    def write_quorum(self) -> int:
        return write_quorum_for(self.replication_factor, self.consistency_mode)

    def is_read_after_write_consistent(self) -> bool:
        return self.read_quorum() + self.write_quorum() > self.replication_factor
