"""Quorum RPC strategies (reference src/rpc/rpc_helper.rs:128-533).

  call / call_many / broadcast — plain fan-out
  try_call_many — parallel calls until `quorum` successes; either
      all-at-once (writes) or preference-ordered staggered sends (reads:
      self > lowest observed rtt, reference rpc_helper.rs:621)
  try_write_many_sets — during layout transitions a write must reach a
      quorum in EVERY active layout version's node set; leftover requests
      keep running in the background so slow nodes still converge
      (reference rpc_helper.rs:432-533)

Every remote call is health-tracked (rpc/peer_health.py): a per-peer
circuit breaker fast-fails calls to known-dead peers, timeouts adapt to
the peer's observed RTT, idempotent calls retry with jittered backoff,
and request_order deprioritizes sick peers.  See
doc/fault-injection.md.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any

from ..net.connection import ConnectionClosed, RemoteError
from ..net.message import PRIO_NORMAL
from ..net.netapp import Endpoint
from ..utils.backoff import Backoff
from ..utils.background import spawn
from ..utils.error import Quorum
from ..utils.metrics import registry
from ..utils.tracing import NOOP_SPAN, tracer
from .peer_health import PeerHealth, PeerUnavailable

logger = logging.getLogger("garage.rpc")

STAGGER_DELAY = 0.2  # launch an extra request if no reply within this
RETRY_BASE = 0.05  # idempotent-call retry backoff (jittered-exponential)
RETRY_MAX = 2.0


def _quorum_fail(lbl: tuple, quorum: int, got: int, errors: list[str]):
    """Count + raise in one place so no Quorum path misses the metric."""
    registry.incr("rpc_quorum_error_counter", lbl)
    raise Quorum(quorum, got, errors)


def _is_transport_error(e: BaseException) -> bool:
    """Failures that say something about the PEER/LINK (feed the breaker,
    eligible for idempotent retry) vs application-level errors."""
    from ..net.netapp import RpcError

    return isinstance(
        e, (asyncio.TimeoutError, ConnectionClosed, OSError, RpcError)
    ) and not isinstance(e, RemoteError)


class RpcHelper:
    def __init__(
        self,
        our_id: bytes,
        peering,
        default_timeout: float = 30.0,
        health: PeerHealth | None = None,
    ):
        self.our_id = our_id
        self.peering = peering
        self.default_timeout = default_timeout
        # per-peer health/breaker state; the composition root shares one
        # instance with the peering layer so ping outcomes feed it too
        self.health = health or PeerHealth(our_id)
        # node_id -> zone name (or None), wired by the composition root
        # from the current cluster layout; used by request_order
        self.zone_of = None

    # --- ordering ------------------------------------------------------------

    def request_order(self, nodes: list[bytes]) -> list[bytes]:
        """Self first, then same-zone nodes, then by ascending observed
        ping rtt (reference rpc_helper.rs:621-648: "priorize ourself, then
        nodes in the same zone, and within a same zone ... lowest
        latency").  Known-sick peers (open breaker / collapsed success
        rate) sort after every healthy one regardless of zone or rtt, so
        staggered reads don't spend their first quorum slots on nodes
        that will fast-fail or stall.  Zone lookup comes from
        `self.zone_of` (wired to the cluster layout by the composition
        root); without it the order degrades to self-then-rtt."""
        our_zone = self.zone_of(self.our_id) if self.zone_of else None

        def key(n: bytes):
            if n == self.our_id:
                return (0, 0, 0, 0.0, n)
            sick = 1 if self.health.is_sick(n) else 0
            other_zone = (
                1 if our_zone is None or self.zone_of(n) != our_zone else 0
            )
            # one RTT view for ordering AND adaptive timeouts: the health
            # EWMA sees every RPC outcome plus pings; peering's ping-only
            # average is the cold-start fallback
            rtt = self.health.rtt_of(n)
            if rtt is None:
                rtt = self.peering.peer_avg_rtt(n)
            return (1, sick, other_zone, rtt if rtt is not None else 9.0, n)

        return sorted(nodes, key=key)

    # --- basic ---------------------------------------------------------------

    async def call(
        self,
        endpoint: Endpoint,
        node: bytes,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: float | None = None,
        stream_factory=None,
        idempotent: bool = False,
        max_attempts: int = 3,
        order_tag=None,
    ):
        """One health-tracked RPC.

        stream_factory() makes a FRESH attached byte stream per call —
        required because an async iterator can only be consumed once but a
        quorum write sends the same payload to several nodes (and a retry
        resends it).

        Breaker: calls to a peer whose circuit is open raise
        PeerUnavailable immediately instead of burning a timeout.  Unless
        the caller pinned `timeout`, the per-call timeout adapts to the
        peer's observed RTT (a historically-fast peer fails in ~1 s, not
        `default_timeout`).

        `idempotent=True` enables jittered-exponential retry (up to
        `max_attempts` total tries) on TRANSPORT failures only — reads
        and other at-least-once-safe calls; application errors
        (RemoteError) never retry."""
        backoff = Backoff(RETRY_BASE, RETRY_MAX)
        attempts = max(1, max_attempts) if idempotent else 1
        lbl = (("endpoint", endpoint.path),)
        last_exc: Exception | None = None
        for attempt in range(attempts):
            if attempt:
                registry.incr("rpc_retry_counter", lbl)
                await asyncio.sleep(backoff.next())
            # each attempt is its own child span (the retry story of a
            # request is visible in the trace: attempt number + what the
            # breaker thought of the peer when the attempt launched)
            cm = (
                tracer.span(
                    "rpc-attempt:" + endpoint.path,
                    attempt=attempt,
                    breaker=self.health.state_of(node),
                    to=node.hex()[:16],
                )
                if tracer.enabled
                else NOOP_SPAN
            )
            try:
                with cm:
                    return await self._call_once(
                        endpoint, node, msg, prio, timeout, stream_factory,
                        order_tag,
                    )
            except PeerUnavailable as e:
                # fast-fail is cheap; retrying it is pointless until the
                # breaker half-opens, which takes longer than our backoff
                raise e
            except (asyncio.TimeoutError, ConnectionClosed, OSError) as e:
                last_exc = e
            except Exception as e:  # noqa: BLE001
                if isinstance(e, RemoteError) or not _is_transport_error(e):
                    raise
                last_exc = e
        assert last_exc is not None
        raise last_exc

    async def _call_once(
        self, endpoint, node, msg, prio, timeout, stream_factory,
        order_tag=None,
    ):
        if node == self.our_id:
            # local shortcut: no transport involved, health not consulted
            return await endpoint.call(
                node, msg, prio=prio, timeout=timeout or self.default_timeout,
                stream=stream_factory() if stream_factory else None,
                order_tag=order_tag,
            )
        health = self.health
        # raises PeerUnavailable when the circuit is open; True = this
        # call owns the half-open probe slot and must release it if it
        # ends without a verdict
        is_probe = health.acquire(node)
        if timeout is not None:
            eff_timeout = timeout
        elif is_probe:
            # the half-open probe gets the full default timeout: it must
            # be able to CLOSE the breaker even when the adaptive window
            # has collapsed below the peer's current response time
            eff_timeout = self.default_timeout
        else:
            eff_timeout = health.adaptive_timeout(node, self.default_timeout)
        t0 = time.perf_counter()
        try:
            resp = await endpoint.call(
                node, msg, prio=prio, timeout=eff_timeout,
                stream=stream_factory() if stream_factory else None,
                order_tag=order_tag,
            )
        except RemoteError:
            # the peer answered (with an application error): transport is
            # healthy — feed the breaker a success, re-raise for the caller
            health.record_success(
                node, time.perf_counter() - t0, probe=is_probe
            )
            raise
        except asyncio.CancelledError:
            if is_probe:
                health.release(node)  # no verdict: free the probe slot
            raise
        except Exception as e:  # noqa: BLE001
            if isinstance(e, asyncio.TimeoutError):
                # widen the peer's adaptive window (TCP-RTO style)
                health.record_failure(
                    node, timed_out_after=eff_timeout, probe=is_probe
                )
            elif _is_transport_error(e):
                health.record_failure(node, probe=is_probe)
            elif is_probe:
                health.release(node)
            raise
        health.record_success(node, time.perf_counter() - t0, probe=is_probe)
        return resp

    async def call_many(
        self,
        endpoint: Endpoint,
        nodes: list[bytes],
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: float | None = None,
    ) -> list[tuple[bytes, Any]]:
        """Call all nodes; returns [(node, Resp | Exception)]."""

        async def one(n):
            try:
                return (n, await self.call(endpoint, n, msg, prio, timeout))
            except Exception as e:  # noqa: BLE001
                return (n, e)

        return list(await asyncio.gather(*[one(n) for n in nodes]))

    async def broadcast(self, endpoint: Endpoint, msg: Any, prio=PRIO_NORMAL):
        nodes = [self.our_id] + list(self.peering.connected_peers())
        return await self.call_many(endpoint, nodes, msg, prio)

    # --- quorum reads/writes --------------------------------------------------

    async def try_call_many(
        self,
        endpoint: Endpoint,
        nodes: list[bytes],
        msg: Any,
        quorum: int,
        prio: int = PRIO_NORMAL,
        timeout: float | None = None,
        all_at_once: bool = True,
    ) -> list[Any]:
        """Returns the first `quorum` successful response bodies, or raises
        `Quorum`.  With all_at_once=False, requests are launched in
        preference order, staggering extras only when replies are slow —
        the read path optimization that keeps traffic off far nodes."""
        nodes = self.request_order(nodes)
        lbl = (("endpoint", endpoint.path),)
        if quorum > len(nodes):
            _quorum_fail(lbl, quorum, 0, [f"only {len(nodes)} candidate nodes"])
        # `timeout` stays None unless the caller pinned it, so each
        # per-node call gets its adaptive (RTT-derived) timeout

        results: list[Any] = []
        errors: list[str] = []
        pending: set[asyncio.Task] = set()
        next_idx = 0

        def launch(n: bytes):
            async def one():
                return await self.call(endpoint, n, msg, prio, timeout)

            t = asyncio.create_task(one())
            t.node = n  # type: ignore[attr-defined]
            pending.add(t)

        initial = len(nodes) if all_at_once else quorum
        for n in nodes[:initial]:
            launch(n)
        next_idx = initial

        try:
            while len(results) < quorum:
                if not pending:
                    _quorum_fail(lbl, quorum, len(results), errors)
                wait_timeout = None if all_at_once else STAGGER_DELAY
                done, _ = await asyncio.wait(
                    pending,
                    timeout=wait_timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done and next_idx < len(nodes):
                    # slow: stagger one more request
                    registry.incr("rpc_stagger_launch_counter", lbl)
                    launch(nodes[next_idx])
                    next_idx += 1
                    continue
                for t in done:
                    pending.discard(t)
                    try:
                        results.append(t.result())
                    except Exception as e:  # noqa: BLE001
                        errors.append(f"{t.node.hex()[:8]}: {e!r}")  # type: ignore[attr-defined]
                        if next_idx < len(nodes):
                            launch(nodes[next_idx])
                            next_idx += 1
            return results[:quorum]
        finally:
            if pending:
                if all_at_once:
                    # write path: surplus requests keep running so slow
                    # replicas still receive the update (reference
                    # rpc_helper.rs non-interrupting strategy)
                    spawn(_drain(pending))
                else:
                    # read path: extra reads are pure cost, cancel them
                    for t in pending:
                        t.cancel()

    async def try_write_many_sets(
        self,
        endpoint: Endpoint,
        write_sets: list[list[bytes]],
        msg: Any,
        quorum: int,
        prio: int = PRIO_NORMAL,
        timeout: float | None = None,
        stream_factory=None,
    ) -> None:
        """Write to the union of all sets; success when EVERY set has
        `quorum` successes.  Remaining in-flight requests are left running
        in the background (they still deliver the write to slow nodes).

        Per-node calls are PINNED to the full timeout, not the adaptive
        RTT-derived one: writes carry whole payloads (block PUT streams),
        and the call only completes once the peer has ingested the entire
        stream — judging that by a ping-scale RTT window would abort
        slow-but-healthy writes and feed their failures to the breaker
        (the EC put path in block/manager.py pins its sends for the same
        reason).  Reads (try_call_many) keep adaptive timeouts: their
        responses are latency-bound, and a stuck read has cheap fallback
        nodes."""
        overall_timeout = timeout if timeout is not None else self.default_timeout
        lbl = (("endpoint", endpoint.path),)
        if not write_sets or all(not s for s in write_sets):
            _quorum_fail(lbl, quorum, 0, ["no write sets (layout has no nodes yet)"])
        all_nodes: list[bytes] = []
        for s in write_sets:
            for n in s:
                if n not in all_nodes:
                    all_nodes.append(n)
        # a write set smaller than the configured quorum can never deliver
        # the promised durability — fail loudly instead of silently
        # lowering the bar (reference rpc_helper.rs errors here too)
        for i, s in enumerate(write_sets):
            if len(s) < quorum:
                _quorum_fail(
                    lbl, quorum, 0,
                    [f"write set {i} has only {len(s)} nodes (< quorum {quorum})"],
                )
        set_success = [0] * len(write_sets)
        set_failed = [0] * len(write_sets)
        errors: list[str] = []
        done_ev = asyncio.Event()

        def sets_satisfied() -> bool:
            return all(s >= quorum for s in set_success)

        def sets_hopeless() -> bool:
            return any(
                len(write_sets[i]) - set_failed[i] < quorum
                for i in range(len(write_sets))
            )

        async def one(n: bytes):
            try:
                await self.call(
                    endpoint, n, msg, prio, overall_timeout,
                    stream_factory=stream_factory,
                )
                for i, s in enumerate(write_sets):
                    if n in s:
                        set_success[i] += 1
            except Exception as e:  # noqa: BLE001
                errors.append(f"{n.hex()[:8]}: {e!r}")
                for i, s in enumerate(write_sets):
                    if n in s:
                        set_failed[i] += 1
            if sets_satisfied() or sets_hopeless():
                done_ev.set()

        tasks = [asyncio.create_task(one(n)) for n in all_nodes]
        try:
            await asyncio.wait_for(done_ev.wait(), overall_timeout + 5.0)
        except asyncio.TimeoutError:
            pass
        if not sets_satisfied():
            for t in tasks:
                t.cancel()
            got = min(set_success) if set_success else 0
            _quorum_fail(lbl, quorum, got, errors)
        # leftover requests continue in the background
        leftover = [t for t in tasks if not t.done()]
        if leftover:
            spawn(_drain(leftover))


async def _drain(tasks):
    await asyncio.gather(*tasks, return_exceptions=True)
