"""Cluster membership, layout and quorum RPC (reference src/rpc/)."""
