"""Tenant observatory: cluster-wide per-tenant usage accounting, SLO
burn, and fairness rollup (ROADMAP item 5's measurement half).

PR 8's overload plane admits and sheds per node, so a tenant hammering
every frontend gets N× its intended budget and no surface can show it —
tenant identity, token consumption, shed counts and SLO burn existed
only node-locally.  This module is the measurement plane the later
enforcement PR (cluster-global budgets, coordinated shedding) will key
off:

  - `TenantObservatory` — a process-wide singleton (PhaseAggregator /
    TrafficObservatory discipline: in-process test nodes share one S3
    frontend path, so per-node instances would double-count) fed by the
    S3 request path AFTER SigV4 verification with the AUTHENTICATED key
    id (op class, bytes in/out, latency into a per-tenant windowed p99),
    and by the admission controller with per-tenant shed counts (keyed
    by the CLAIMED id — the only identity that exists at shed time) and
    queue waits.  Cardinality-bounded by construction: a Space-Saving
    top-K over tenant ids gates which tenants get an exact row; under
    the cap every row is exact, over it the coldest tenant's row is
    evicted (utils/sketch.py upper-bound discipline).

  - per-tenant SLO classes: `[tenants]` config maps class name ->
    availability target + latency target + member key ids; each
    tenant's window counters drive SloTracker-style burn against its
    own class targets.

  - surfaces: a bounded `tn.*` digest section gossiped on the existing
    anti-entropy exchange (additive keys, DIGEST_VERSION stays 1),
    federated as admin `GET /v1/cluster/tenants` + admin-RPC `tenants`
    (cluster-summed per-tenant consumption, fairness stats, per-node
    failure list like `/v1/cluster/durability`), numeric-only
    `cluster_node_tenant_*` families on `/metrics/cluster` (tenant
    NAMES stay in JSON, never labels — the PR 12 cardinality rule),
    CLI `cluster tenants`, a `hog` column in `cluster top`, and a
    rate-bounded `tenant-hog` warn flight event that lands in the
    skew-corrected `cluster events` timeline.
"""

from __future__ import annotations

import logging
import math
import time
from collections import deque

from ..utils import metrics as metrics_mod
from ..utils.sketch import SpaceSaving
from .traffic import OP_KINDS, classify_op  # noqa: F401 — shared op taxonomy

logger = logging.getLogger("garage.tenant")

# class assigned to any authenticated key not listed under a `[tenants]`
# class — its targets come from the `default` class when one is
# configured, else these built-ins (mirrors `[admin] slo_*` defaults)
DEFAULT_CLASS = "default"
DEFAULT_AVAILABILITY_TARGET = 99.9
DEFAULT_LATENCY_TARGET_MSEC = 1000.0

# per-tenant latency ring: enough samples for a stable p99 without
# unbounded growth (the cardinality bound already caps row count)
_LAT_SAMPLES = 256

_LN2 = math.log(2.0)


def class_for(config, key_id: str) -> tuple[str, float, float]:
    """Resolve a key id to its `(class name, availability target frac,
    latency target secs)` from the LIVE `[tenants]` config (tests and
    operators mutate config post-construction).  Unknown keys fall to
    the `default` class."""
    tenants = getattr(config, "tenants", None) or {}
    cls, tc = None, None
    for name, c in tenants.items():
        if key_id in (c.keys or ()):
            cls, tc = name, c
            break
    if tc is None:
        cls, tc = DEFAULT_CLASS, tenants.get(DEFAULT_CLASS)
    avail = (
        tc.availability_target if tc is not None
        else DEFAULT_AVAILABILITY_TARGET
    )
    lat_ms = (
        tc.latency_target_msec if tc is not None
        else DEFAULT_LATENCY_TARGET_MSEC
    )
    return cls, min(float(avail), 100.0) / 100.0, float(lat_ms) / 1000.0


class TenantObservatory:
    """Streaming per-process per-tenant usage summary.  All updates are
    O(1) dict/sketch arithmetic — safe on the request path, no I/O."""

    # rolling window for per-tenant burn (SloTracker discipline: the
    # oldest in-window snapshot vs now, so scrape rate can't change the
    # math); snapshots coalesce at 1 s so the deque stays bounded
    window = 600.0
    _snap_coalesce = 1.0

    def __init__(
        self,
        topk: int = 64,
        halflife: float | None = 600.0,
        clock=time.monotonic,
    ):
        self.topk = int(topk)
        self.halflife = halflife
        self.clock = clock
        self.enabled = False
        # per-CLASS exposition counters ride the process registry: class
        # names are config-declared (bounded), unlike tenant key ids
        # which never become labels.  Injectable for per-node tests.
        self.registry = metrics_mod.registry
        # key id -> class NAME for pre-auth sheds (set by model/garage.py
        # against its live config; None means "default")
        self.class_resolver = None
        self._reset_state()

    def _reset_state(self) -> None:
        # the sketch decides WHICH tenants deserve an exact row: every
        # tracked row's key is in sketch.counts, so len(rows) <= topk is
        # structural, and "hot" means hot NOW (decayed weights)
        self.sketch = SpaceSaving(
            self.topk, halflife=self.halflife, clock=self.clock
        )
        self.tenants: dict[str, dict] = {}
        self.mismatches = 0
        self.total_sheds = 0

    def reset(self) -> None:
        """Drop all accumulated state (test/bench isolation — the
        singleton outlives any one in-process node)."""
        self._reset_state()

    def reconfigure(self, topk: int, halflife: float | None) -> None:
        """Apply sizing knobs; resets state only when they changed (the
        sketch's capacity is baked into its eviction bound)."""
        if (int(topk), halflife) == (self.topk, self.halflife):
            return
        self.topk = int(topk)
        self.halflife = halflife
        self._reset_state()

    # --- row admission (the cardinality bound) -------------------------------

    def _new_row(self) -> dict:
        return {
            "ops": dict.fromkeys(OP_KINDS, 0),
            "bin": 0,       # request payload bytes (tenant -> cluster)
            "bout": 0,      # response payload bytes (cluster -> tenant)
            "lat": deque(maxlen=_LAT_SAMPLES),
            "shed": 0,
            "qw_n": 0,
            "qw_s": 0.0,
            "req": 0,       # cumulative requests (availability window)
            "err": 0,       # cumulative 5xx
            "lat_n": 0,     # cumulative latency-observed
            "lat_over": 0,  # cumulative over-target
            "cls": DEFAULT_CLASS,
            "avail_t": DEFAULT_AVAILABILITY_TARGET / 100.0,
            "lat_t": DEFAULT_LATENCY_TARGET_MSEC / 1000.0,
            # (t, req, err, lat_n, lat_over) window snapshots
            "snaps": deque(),
        }

    def _row(self, key_id: str, weight: float = 1.0) -> dict:
        """Admit `key_id` through the Space-Saving gate and return its
        exact row.  Over capacity the newcomer evicts the coldest
        tenant's row (its sketch count carries the upper bound); rows
        whose key fell out of the sketch are pruned so the row dict can
        never outgrow the sketch."""
        self.sketch.incr(key_id, weight)
        row = self.tenants.get(key_id)
        if row is None:
            row = self._new_row()
            self.tenants[key_id] = row
            if len(self.tenants) > len(self.sketch.counts):
                for k in list(self.tenants):
                    if k not in self.sketch.counts:
                        del self.tenants[k]
        return row

    # --- the S3 request-path hooks -------------------------------------------

    def record_request(
        self,
        key_id: str,
        op: str,
        bytes_in: int,
        bytes_out: int,
        secs: float,
        is_err: bool,
        queued_secs: float = 0.0,
        tenant_class: tuple[str, float, float] | None = None,
    ) -> None:
        """One admitted, AUTHENTICATED S3 request (shed 503s never get
        here — the overload plane's invariant; they arrive via
        record_shed keyed by the claimed id).  `tenant_class` is
        `class_for(...)`'s triple, resolved by the caller against its
        live config.  Must never raise: it runs in the request
        handler's finally."""
        if not self.enabled or not key_id:
            return
        row = self._row(key_id)
        if tenant_class is not None:
            row["cls"], row["avail_t"], row["lat_t"] = tenant_class
        row["ops"][op if op in row["ops"] else "other"] += 1
        row["bin"] += max(0, int(bytes_in or 0))
        row["bout"] += max(0, int(bytes_out or 0))
        row["lat"].append(secs)
        if queued_secs:
            row["qw_n"] += 1
            row["qw_s"] += queued_secs
        row["req"] += 1
        if is_err:
            row["err"] += 1
        row["lat_n"] += 1
        over = secs > row["lat_t"]
        if over:
            row["lat_over"] += 1
        # class-level counters (Grafana per-class burn panels): the
        # `class` label's value set is config-bounded and enrolled in
        # BOUNDED_LABEL_VALUES (script/dashboard_lint.py)
        lbl = (("class", row["cls"]),)
        self.registry.incr("api_tenant_class_requests_total", lbl)
        if is_err:
            self.registry.incr("api_tenant_class_errors_total", lbl)
        if over:
            self.registry.incr("api_tenant_class_over_latency_total", lbl)

    def record_shed(self, key_id: str) -> None:
        """One admission shed, keyed by the CLAIMED key id — the only
        identity that exists at shed time (pre-SigV4).  A pure-shed
        abuser must still surface, so sheds ride the same Space-Saving
        admission as requests."""
        if not self.enabled or not key_id:
            return
        self.total_sheds += 1
        self._row(key_id)["shed"] += 1
        cls = None
        if self.class_resolver is not None:
            try:
                cls = self.class_resolver(key_id)
            except Exception:  # noqa: BLE001
                # a broken resolver must not turn a shed into a crash
                cls = None  # graft-lint: allow-swallow(shed still counts, under the default class)
        self.registry.incr(
            "api_tenant_class_sheds_total",
            (("class", cls or DEFAULT_CLASS),),
        )

    def record_mismatch(self) -> None:
        """Claimed key id != authenticated key id (spoofed or mangled
        Credential): counted, never attributed to a tenant row."""
        if not self.enabled:
            return
        self.mismatches += 1

    # --- derived numbers ------------------------------------------------------

    @property
    def total_ops(self) -> int:
        return sum(sum(r["ops"].values()) for r in self.tenants.values())

    def _rate(self, count: float) -> float:
        """Approximate ops/s of a decayed sketch count (the decayed
        counter equilibrates at r * halflife / ln 2)."""
        if self.halflife:
            return count * _LN2 / self.halflife
        return 0.0

    def _p99(self, row: dict) -> float | None:
        lat = row["lat"]
        if not lat:
            return None
        s = sorted(lat)
        return s[min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.999))]

    def _burn(self, row: dict) -> dict:
        """SloTracker-style burn for one tenant against its class
        targets: bad-fraction over the rolling window divided by the
        allowed fraction.  Returns window counts too so the federated
        rollup can re-derive an exact cluster-wide burn from sums."""
        now = self.clock()
        snaps = row["snaps"]
        cur = (now, row["req"], row["err"], row["lat_n"], row["lat_over"])
        if snaps and now - snaps[-1][0] < self._snap_coalesce:
            snaps[-1] = cur
        else:
            snaps.append(cur)
        while snaps and now - snaps[0][0] > self.window:
            snaps.popleft()
        first = snaps[0]
        a_n, a_bad = cur[1] - first[1], cur[2] - first[2]
        l_n, l_bad = cur[3] - first[3], cur[4] - first[4]
        # the window's boundary snapshot itself holds the oldest counts:
        # with a single snapshot the deltas are 0 (no window yet), so
        # fall back to the cumulative counters — a fresh tenant's first
        # errors must burn immediately, not after the coalesce interval
        if a_n == 0 and l_n == 0 and len(snaps) == 1:
            a_n, a_bad = cur[1], cur[2]
            l_n, l_bad = cur[3], cur[4]
        a_allowed = max(1.0 - row["avail_t"], 1e-9)
        l_allowed = a_allowed

        def burn(n, bad, allowed):
            return (bad / n) / allowed if n > 0 else 0.0

        ab = burn(a_n, a_bad, a_allowed)
        lb = burn(l_n, l_bad, l_allowed)
        return {
            "avail": round(ab, 4),
            "lat": round(lb, 4),
            "worst": round(max(ab, lb), 4),
            "an": a_n,
            "abad": a_bad,
            "ln": l_n,
            "lbad": l_bad,
        }

    # --- serializations -------------------------------------------------------

    def snapshot(self, top_n: int = 20) -> dict:
        """The local half of `GET /v1/cluster/tenants`: exact rows for
        the top-N tenants by decayed weight."""
        rows = []
        total = max(self.total_ops, 1)
        for key_id, c, e in self.sketch.top(top_n):
            row = self.tenants.get(key_id)
            if row is None:
                continue
            b = self._burn(row)
            ops_total = sum(row["ops"].values())
            p99 = self._p99(row)
            rows.append(
                {
                    "id": key_id,
                    "class": row["cls"],
                    "ops": ops_total,
                    "opMix": {k: v for k, v in row["ops"].items() if v},
                    "opsPerSec": round(self._rate(c), 4),
                    "share": round(ops_total / total, 4),
                    "bytesIn": row["bin"],
                    "bytesOut": row["bout"],
                    "p99Ms": round(p99 * 1000, 3) if p99 is not None else None,
                    "queueWaitMeanMs": (
                        round(row["qw_s"] / row["qw_n"] * 1000, 3)
                        if row["qw_n"]
                        else None
                    ),
                    "shed": row["shed"],
                    "burn": {
                        "availability": b["avail"],
                        "latency": b["lat"],
                        "worst": b["worst"],
                    },
                    "sketchWeight": round(c, 2),
                    "sketchError": round(e, 2),
                }
            )
        return {
            "trackedTenants": len(self.tenants),
            "totalOps": self.total_ops,
            "sheds": self.total_sheds,
            "claimedMismatches": self.mismatches,
            "tenants": rows,
            "decayHalflifeSecs": self.halflife,
            "windowSecs": self.window,
        }

    def digest_fields(self, rps: float = 0.0, top_n: int = 5) -> dict:
        """Compact `tn.*` block for the gossiped node digest (additive
        keys, DIGEST_VERSION stays 1).  `rps` is the collector's
        windowed op rate.  Bounded: scalar summary + top-N rows; tenant
        ids appear as JSON VALUES only, never metric labels."""
        total = max(self.total_ops, 1)
        rows = []
        wburn = 0.0
        top1 = 0.0
        for key_id, c, _e in self.sketch.top(top_n):
            row = self.tenants.get(key_id)
            if row is None:
                continue
            b = self._burn(row)
            wburn = max(wburn, b["worst"])
            ops_total = sum(row["ops"].values())
            top1 = max(top1, ops_total / total)
            rows.append(
                {
                    "id": key_id,
                    "cls": row["cls"],
                    "ops": ops_total,
                    "rps": round(self._rate(c), 4),
                    "by": row["bin"] + row["bout"],
                    "shed": row["shed"],
                    "burn": b["worst"],
                    "an": b["an"],
                    "abad": b["abad"],
                    "ln": b["ln"],
                    "lbad": b["lbad"],
                }
            )
        # worst burn must scan EVERY row, not just the top-N by weight:
        # a small tenant blowing its budget is exactly the signal
        for row in self.tenants.values():
            if len(rows) >= len(self.tenants):
                break
            wburn = max(wburn, self._burn(row)["worst"])
        return {
            "trk": len(self.tenants),
            "ops": self.total_ops,
            "rps": round(rps, 4),
            "shed": self.total_sheds,
            "mm": self.mismatches,
            "top1": round(top1, 4),
            "wburn": round(wburn, 4),
            "rows": rows,
        }


# process-wide observatory: the S3 frontends of every in-process node
# feed it (PhaseAggregator pattern — per-node instances would
# double-count through the shared request path)
observatory = TenantObservatory()

_refs = 0


def enable(topk: int | None = None, halflife: float | None = None) -> None:
    """Refcounted attach (every in-process Garage with `[admin]
    tenant_observatory` calls this at start).  Sizing knobs apply only
    on the 0 -> 1 transition — reconfiguring mid-flight would reset the
    rows under the other nodes."""
    global _refs
    if _refs == 0 and topk is not None:
        observatory.reconfigure(topk, halflife)
    _refs += 1
    observatory.enabled = True


def disable() -> None:
    global _refs
    _refs = max(0, _refs - 1)
    if _refs == 0:
        observatory.enabled = False


# --- cluster rollup + the one serialization per endpoint ----------------------


def _tenant_rows(garage) -> list[dict]:
    """Per-node `tn` digest rows from the gossip state.  A digest-less
    old peer renders a clean row with `tenant: null` — never an error,
    never dropped (the `/v1/cluster/durability` per-node-failure-list
    discipline)."""
    from .telemetry_digest import _valid_digest

    system = garage.system
    system.expire_node_status()
    local = _valid_digest(garage.telemetry.collect()) or {}
    rows = [
        {
            "id": system.id.hex(),
            "isSelf": True,
            "isUp": True,
            "tenant": local.get("tn"),
        }
    ]
    for pid, (pst, _ts) in sorted(system.node_status.items()):
        d = _valid_digest(pst.telemetry) or {}
        rows.append(
            {
                "id": pid.hex(),
                "isSelf": False,
                "isUp": system.netapp.is_connected(pid),
                "tenant": d.get("tn"),
            }
        )
    return rows


def _num(v) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


# rate bound for the tenant-hog flight event: one emission per tenant
# per this many seconds — the rollup runs on every scrape/CLI refresh
# and the timeline must not drown in repeats
_HOG_EVENT_MIN_INTERVAL = 60.0
_hog_last: dict[str, float] = {}


def _maybe_hog_event(garage, hog: dict) -> None:
    """Emit the `tenant-hog` warn flight event (rate-bounded per
    tenant), landing in the node's flight recorder and from there in
    the merged skew-corrected `cluster events` timeline."""
    now = time.monotonic()
    last = _hog_last.get(hog["id"])
    if last is not None and now - last < _HOG_EVENT_MIN_INTERVAL:
        return
    _hog_last[hog["id"]] = now
    try:
        from ..utils.flight import record_event

        record_event(
            "tenant-hog",
            {
                "tenant": hog["id"],
                "class": hog.get("class"),
                "share": round(hog["share"], 4),
                "fair_share": round(hog["fairShare"], 4),
                "multiple": round(hog["multiple"], 2),
                "warn_multiple": hog["warnMultiple"],
            },
            severity="warn",
        )
    except Exception as e:  # noqa: BLE001
        # graft-lint: allow-swallow(observability-of-observability: a broken flight recorder must not fail the tenants endpoint)
        logger.debug("tenant-hog event emission failed: %r", e)


def tenants_response(garage) -> dict:
    """The one serialization of the tenant observatory, shared by admin
    `GET /v1/cluster/tenants`, the admin-RPC `tenants` op and the
    `cluster tenants` CLI (key casing cannot drift between transports).

    Cluster rows come from the gossiped `tn.*` digest keys, so any node
    answers for all; the per-tenant table sums consumption across every
    reporting node's top-N rows, and cluster-wide burn is re-derived
    from the summed window counts (exact where the digests carry the
    tenant, a lower bound where a node's top-N cut dropped it)."""
    rows = _tenant_rows(garage)
    with_tn = [r for r in rows if isinstance(r.get("tenant"), dict)]

    # cluster-summed per-tenant table keyed by tenant id
    table: dict[str, dict] = {}
    for r in with_tn:
        for t in r["tenant"].get("rows") or []:
            if not isinstance(t, dict) or not t.get("id"):
                continue
            e = table.setdefault(
                str(t["id"]),
                {
                    "class": t.get("cls"),
                    "ops": 0.0,
                    "opsPerSec": 0.0,
                    "bytes": 0.0,
                    "shed": 0.0,
                    "an": 0.0,
                    "abad": 0.0,
                    "ln": 0.0,
                    "lbad": 0.0,
                    "burnMaxNode": 0.0,
                    "nodes": 0,
                },
            )
            e["class"] = t.get("cls") or e["class"]
            e["ops"] += _num(t.get("ops"))
            e["opsPerSec"] += _num(t.get("rps"))
            e["bytes"] += _num(t.get("by"))
            e["shed"] += _num(t.get("shed"))
            e["an"] += _num(t.get("an"))
            e["abad"] += _num(t.get("abad"))
            e["ln"] += _num(t.get("ln"))
            e["lbad"] += _num(t.get("lbad"))
            e["burnMaxNode"] = max(e["burnMaxNode"], _num(t.get("burn")))
            e["nodes"] += 1

    # cluster-wide burn per tenant from the summed window counts,
    # against the class targets as THIS node's config resolves them
    tenants_cfg = getattr(garage.config, "tenants", None) or {}
    tenant_list = []
    total_ops = sum(e["ops"] for e in table.values()) or 1.0
    for tid, e in table.items():
        tc = tenants_cfg.get(e["class"]) if e["class"] else None
        avail = (
            min(float(tc.availability_target), 100.0) / 100.0
            if tc is not None
            else DEFAULT_AVAILABILITY_TARGET / 100.0
        )
        allowed = max(1.0 - avail, 1e-9)
        ab = (e["abad"] / e["an"]) / allowed if e["an"] > 0 else 0.0
        lb = (e["lbad"] / e["ln"]) / allowed if e["ln"] > 0 else 0.0
        tenant_list.append(
            {
                "id": tid,
                "class": e["class"],
                "ops": e["ops"],
                "opsPerSec": round(e["opsPerSec"], 4),
                "bytes": e["bytes"],
                "shed": e["shed"],
                "share": round(e["ops"] / total_ops, 4),
                "nodesReporting": e["nodes"],
                "burn": {
                    "availability": round(ab, 4),
                    "latency": round(lb, 4),
                    "worst": round(max(ab, lb, e["burnMaxNode"]), 4),
                },
            }
        )
    tenant_list.sort(key=lambda t: (-t["ops"], t["id"]))

    # fairness stats over the cluster-summed consumption
    warn_multiple = garage.config.admin.tenant_hog_share
    n_tenants = len(tenant_list)
    shares = [t["share"] for t in tenant_list]
    fair = 1.0 / n_tenants if n_tenants else 0.0
    med = sorted(t["ops"] for t in tenant_list)[n_tenants // 2] if n_tenants else 0.0
    fairness = {
        "tenants": n_tenants,
        "fairShare": round(fair, 4),
        "top1Share": round(max(shares), 4) if shares else 0.0,
        "maxMedianRatio": (
            round(tenant_list[0]["ops"] / med, 2) if med > 0 else None
        ),
        "worstBurn": (
            round(max(t["burn"]["worst"] for t in tenant_list), 4)
            if tenant_list
            else 0.0
        ),
        "hogShareWarnMultiple": warn_multiple,
    }

    # hog verdict: the top tenant's cluster-wide share vs a fair-share
    # multiple — needs >= 2 tenants (a sole tenant owning 100% is not
    # hogging anything)
    hog = None
    if n_tenants >= 2 and tenant_list[0]["share"] > warn_multiple * fair:
        t0 = tenant_list[0]
        hog = {
            "id": t0["id"],
            "class": t0["class"],
            "share": t0["share"],
            "fairShare": fair,
            "multiple": round(t0["share"] / fair, 2) if fair else None,
            "warnMultiple": warn_multiple,
        }
        _maybe_hog_event(garage, hog)

    return {
        "node": garage.node_id.hex(),
        "enabled": _refs > 0,
        "local": observatory.snapshot(),
        "cluster": {
            "nodes": rows,
            "nodesReporting": len(with_tn),
            "aggregate": {
                "trackedTenants": (
                    max(_num(r["tenant"].get("trk")) for r in with_tn)
                    if with_tn
                    else 0
                ),
                "ops": sum(_num(r["tenant"].get("ops")) for r in with_tn),
                "opsPerSec": round(
                    sum(_num(r["tenant"].get("rps")) for r in with_tn), 4
                ),
                "sheds": sum(
                    _num(r["tenant"].get("shed")) for r in with_tn
                ),
                "claimedMismatches": sum(
                    _num(r["tenant"].get("mm")) for r in with_tn
                ),
            },
            "tenants": tenant_list,
            "fairness": fairness,
            "hog": hog,
        },
    }
