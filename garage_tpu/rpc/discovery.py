"""External peer-discovery publishers: Consul + Kubernetes.

Reference src/rpc/consul.rs (ConsulDiscovery: catalog/agent APIs) and
src/rpc/kubernetes.rs (GarageNode custom resources).  Each publisher can
(a) advertise this node's (public key, rpc address) and (b) list the
other advertised nodes; the System discovery loop connects to whatever
comes back.  Plain aiohttp against the services' REST APIs — no vendored
clients.
"""

from __future__ import annotations

import json
import logging
import socket

logger = logging.getLogger("garage.discovery")

META_PREFIX = "garage-tpu"


class ConsulDiscovery:
    """Publish/fetch via a Consul server (reference consul.rs:76-230).

    api = "agent"  -> PUT /v1/agent/service/register (local agent)
    api = "catalog"-> PUT /v1/catalog/register (direct catalog write)
    reads always use GET /v1/catalog/service/{service_name}.
    """

    def __init__(self, cfg):
        self.addr = cfg.consul_http_addr.rstrip("/")
        self.service_name = cfg.service_name
        self.api = cfg.api
        self.token = cfg.token
        self.tags = list(cfg.tags or [])
        self.meta = dict(cfg.meta or {})
        # TLS knobs (reference config.rs ConsulDiscoveryConfig + consul.rs
        # client builder): private CA, mutual-TLS client cert, skip-verify
        self.ca_cert = cfg.ca_cert
        self.client_cert = cfg.client_cert
        self.client_key = cfg.client_key
        self.tls_skip_verify = cfg.tls_skip_verify
        self._session = None

    def _ssl(self):
        """ssl.SSLContext for the consul endpoint, or None for defaults."""
        if not (self.ca_cert or self.client_cert or self.tls_skip_verify):
            return None
        import ssl

        ctx = ssl.create_default_context(cafile=self.ca_cert)
        if self.client_cert:
            ctx.load_cert_chain(self.client_cert, self.client_key)
        if self.tls_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def _sess(self):
        import aiohttp

        if self._session is None or self._session.closed:
            headers = {}
            if self.token:
                headers["x-consul-token"] = self.token
            ssl_ctx = self._ssl()
            connector = (
                aiohttp.TCPConnector(ssl=ssl_ctx) if ssl_ctx is not None else None
            )
            self._session = aiohttp.ClientSession(
                headers=headers, connector=connector
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def get_nodes(self) -> list[tuple[bytes, tuple[str, int]]]:
        url = f"{self.addr}/v1/catalog/service/{self.service_name}"
        async with self._sess().get(url) as resp:
            resp.raise_for_status()
            entries = await resp.json()
        out = []
        for ent in entries:
            meta = ent.get("ServiceMeta") or {}
            pubkey = meta.get(f"{META_PREFIX}-pubkey")
            ip = ent.get("ServiceAddress") or ent.get("Address")
            port = ent.get("ServicePort")
            if not (pubkey and ip and port):
                logger.warning("malformed consul node spec: %r", ent)
                continue
            try:
                out.append((bytes.fromhex(pubkey), (ip, int(port))))
            except ValueError:
                logger.warning("bad pubkey from consul: %r", pubkey)
        return out

    async def publish(self, node_id: bytes, rpc_addr: tuple[str, int]) -> None:
        hostname = socket.gethostname()
        node = f"garage:{node_id.hex()[:16]}"
        meta = dict(self.meta)
        meta[f"{META_PREFIX}-pubkey"] = node_id.hex()
        meta[f"{META_PREFIX}-hostname"] = hostname
        tags = ["advertised-by-garage-tpu", hostname, *self.tags]
        if self.api == "catalog":
            url = f"{self.addr}/v1/catalog/register"
            body = {
                "Node": node,
                "Address": rpc_addr[0],
                "Service": {
                    "ID": node,
                    "Service": self.service_name,
                    "Tags": tags,
                    "Meta": meta,
                    "Address": rpc_addr[0],
                    "Port": rpc_addr[1],
                },
            }
        else:
            url = f"{self.addr}/v1/agent/service/register?replace-existing-checks"
            body = {
                "ID": node,
                "Name": self.service_name,
                "Tags": tags,
                "Meta": meta,
                "Address": rpc_addr[0],
                "Port": rpc_addr[1],
            }
        async with self._sess().put(url, json=body) as resp:
            resp.raise_for_status()


class KubernetesDiscovery:
    """Publish/fetch via GarageNode custom resources in the cluster API
    (reference kubernetes.rs:1-114).  Runs in-cluster: credentials come
    from the mounted service account unless overridden (tests point
    api_server at a mock and set token/verify off)."""

    GROUP = "deuxfleurs.fr"
    VERSION = "v1"
    PLURAL = "garagenodes"

    def __init__(self, cfg):
        self.namespace = cfg.namespace
        self.service_name = cfg.service_name
        self.skip_crd = cfg.skip_crd
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        self.api_server = cfg.api_server or "https://kubernetes.default.svc"
        self.token = cfg.token
        self.ca_cert: str | None = None
        if cfg.token is None:
            try:
                with open(f"{sa}/token") as f:
                    self.token = f.read().strip()
                self.ca_cert = f"{sa}/ca.crt"
            except OSError:
                self.token = None
        self._session = None

    def _sess(self):
        import aiohttp
        import ssl

        if self._session is None or self._session.closed:
            headers = {}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            ssl_ctx = None
            if self.api_server.startswith("https") and self.ca_cert:
                ssl_ctx = ssl.create_default_context(cafile=self.ca_cert)
            self._session = aiohttp.ClientSession(
                headers=headers,
                connector=aiohttp.TCPConnector(ssl=ssl_ctx)
                if ssl_ctx is not None
                else None,
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def _base(self) -> str:
        return (
            f"{self.api_server}/apis/{self.GROUP}/{self.VERSION}"
            f"/namespaces/{self.namespace}/{self.PLURAL}"
        )

    async def get_nodes(self) -> list[tuple[bytes, tuple[str, int]]]:
        sel = f"garage.{self.GROUP}/service={self.service_name}"
        async with self._sess().get(
            self._base(), params={"labelSelector": sel}
        ) as resp:
            resp.raise_for_status()
            data = await resp.json()
        out = []
        for item in data.get("items", []):
            name = (item.get("metadata") or {}).get("name", "")
            spec = item.get("spec") or {}
            ip, port = spec.get("address"), spec.get("port")
            if not (name and ip and port):
                logger.warning("malformed GarageNode: %r", item)
                continue
            try:
                out.append((bytes.fromhex(name), (ip, int(port))))
            except ValueError:
                logger.warning("bad GarageNode name (want hex pubkey): %r", name)
        return out

    async def publish(self, node_id: bytes, rpc_addr: tuple[str, int]) -> None:
        name = node_id.hex()
        body = {
            "apiVersion": f"{self.GROUP}/{self.VERSION}",
            "kind": "GarageNode",
            "metadata": {
                "name": name,
                "labels": {
                    f"garage.{self.GROUP}/service": self.service_name,
                },
            },
            "spec": {
                "hostname": socket.gethostname(),
                "address": rpc_addr[0],
                "port": rpc_addr[1],
            },
        }
        # server-side apply: one PATCH upserts (create or update)
        url = f"{self._base()}/{name}?fieldManager=garage-tpu&force=true"
        async with self._sess().patch(
            url,
            data=json.dumps(body),
            headers={"Content-Type": "application/apply-patch+yaml"},
        ) as resp:
            resp.raise_for_status()


def discovery_from_config(config) -> list:
    out = []
    if getattr(config, "consul_discovery", None) is not None:
        out.append(ConsulDiscovery(config.consul_discovery))
    if getattr(config, "kubernetes_discovery", None) is not None:
        out.append(KubernetesDiscovery(config.kubernetes_discovery))
    return out
