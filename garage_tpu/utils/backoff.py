"""Shared exponential-backoff policy.

One implementation for every retry loop in the tree — block resync's
1 min -> 64 min error ladder (block/resync.py), peering's reconnect
pacing (net/peering.py), and the RPC layer's idempotent-call retries
(rpc/rpc_helper.py) — so cap/jitter behavior can't drift between them.

Two shapes:

  - `expo(count, base, max_)`   — pure function of the attempt count
    (deterministic; persisted-counter loops like resync use this)
  - `jittered(delay, rng)`      — multiply by a uniform [0.75, 1.25)
    factor so a thundering herd of retriers decorrelates
  - `Backoff`                   — stateful next()/reset() for in-memory
    retry loops (RPC retries): jittered-exponential with reset-on-success
"""

from __future__ import annotations

import random

JITTER_SPREAD = 0.5  # total width of the jitter factor window


def expo(count: int, base: float, max_: float, factor: float = 2.0) -> float:
    """base * factor**count, capped at max_ (count capped to avoid
    astronomically large intermediates)."""
    return min(max_, base * factor ** min(max(count, 0), 30))


def jittered(delay: float, rng: random.Random | None = None) -> float:
    """delay scaled by a uniform factor in [0.75, 1.25)."""
    r = rng.random() if rng is not None else random.random()
    return delay * (1.0 - JITTER_SPREAD / 2 + JITTER_SPREAD * r)


class Backoff:
    """Jittered-exponential retry pacing with reset-on-success.

    >>> b = Backoff(base=0.1, max_=2.0)
    >>> b.next()   # ~0.1 (jittered)
    >>> b.next()   # ~0.2
    >>> b.reset()  # success observed: next() is back at ~base
    """

    def __init__(
        self,
        base: float,
        max_: float,
        factor: float = 2.0,
        rng: random.Random | None = None,
    ):
        self.base = base
        self.max_ = max_
        self.factor = factor
        self.rng = rng
        self.attempt = 0

    def next(self) -> float:
        d = jittered(expo(self.attempt, self.base, self.max_, self.factor), self.rng)
        self.attempt += 1
        return d

    def reset(self) -> None:
        self.attempt = 0
