"""Common error type (reference src/util/error.rs)."""

from __future__ import annotations


class Error(Exception):
    """Base error for garage_tpu internals."""


class Message(Error):
    pass


class UnexpectedRpcMessage(Error):
    pass


class Timeout(Error):
    pass


class Quorum(Error):
    """Quorum not reached.

    Mirrors reference src/util/error.rs Quorum variant: carries how many
    successes were needed vs obtained and the individual errors.
    """

    def __init__(self, needed: int, got: int, errors: list[str]):
        super().__init__(
            f"could not reach quorum: {got}/{needed} successes; errors: {errors}"
        )
        self.needed = needed
        self.got = got
        self.errors = errors


OkOrMessage = None  # placeholder alias kept for parity with util::error naming
