"""Fixed 32-byte identifiers and content hashes.

Mirrors reference src/util/data.rs:9 (FixedBytes32 / Uuid / Hash): node ids,
object-version uuids and block hashes are all 32-byte values, ordered
lexicographically, rendered as lowercase hex.  Content hashing uses
BLAKE2b-256 (hashlib, same construction as the reference's blake2 crate).
"""

from __future__ import annotations

import hashlib
import os

# A FixedBytes32 is simply `bytes` of length 32; these aliases document intent.
FixedBytes32 = bytes
Uuid = bytes
Hash = bytes

ZERO32: bytes = b"\x00" * 32


def gen_uuid() -> Uuid:
    """Random 128-bit-entropy 32-byte uuid (reference src/util/data.rs:122)."""
    return os.urandom(32)


def blake2sum(data: bytes) -> Hash:
    """Content hash: BLAKE2b-512 truncated to 32 bytes — same construction
    as the reference (src/util/data.rs:129-138), NOT blake2b with
    digest_size=32 (different parameter block, different output)."""
    return hashlib.blake2b(data).digest()[:32]


def sha256sum(data: bytes) -> Hash:
    return hashlib.sha256(data).digest()


def md5sum(data: bytes) -> bytes:
    return hashlib.md5(data).digest()


def hex_of(b: bytes) -> str:
    return b.hex()


def parse_hex(s: str) -> bytes:
    b = bytes.fromhex(s)
    if len(b) != 32:
        raise ValueError(f"expected 32 bytes, got {len(b)}")
    return b


def fixed_from_str(s: str) -> FixedBytes32:
    """Hash a human string into an id (used for bucket ids in tests)."""
    return blake2sum(s.encode())


def xxh3_u64(data: bytes) -> int:
    """64-bit non-cryptographic hash (reference src/util/data.rs:141 uses
    xxhash; stdlib has none, so we take the first 8 bytes of blake2b —
    only used for non-persisted in-memory purposes)."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


def hash_partition_prefix(h: bytes) -> int:
    """Top 16 bits of a hash — used with PARTITION_BITS to derive partition.

    Reference src/rpc/layout/version.rs:101-104 uses the top 8 bits (256
    partitions); we keep the helper generic and mask in the layout code.
    """
    return (h[0] << 8) | h[1]
