"""Span tracing with OTLP/HTTP export (reference: OpenTelemetry spans
around every RPC/API/table op, exported via OTLP when `admin.trace_sink`
is configured — src/garage/tracing_setup.rs:13-37, src/rpc/rpc_helper.rs:172-217).

Design: a contextvar carries the current span, so `with span("name"):`
nests correctly across asyncio task boundaries (contextvars propagate
into tasks).  Finished spans buffer in memory and a background flusher
POSTs them as OTLP/HTTP JSON (`/v1/traces`) to the sink.  When no sink is
configured the API is a near-zero-cost no-op — the hot paths stay hot.

Span ids follow W3C sizes: 16-byte trace id, 8-byte span id.

Cross-node propagation (Dapper-style): `tracer.inject()` serializes the
current span as a compact binary traceparent — 16-byte trace id + 8-byte
parent span id + 1 flag byte (0x01 = sampled), the W3C traceparent
fields without the hex framing — which the RPC layer carries inside the
request frame (`net/connection.py` meta key "tp").  The receiving node
calls `tracer.extract()` and opens its handler span with
`remote_parent=...`, so one S3 PUT against a multi-node cluster yields
ONE trace whose `rpc-handle:*` spans on remote nodes share the root
trace id.  Hot paths guard with `if tracer.enabled` and fall back to the
shared `NOOP_SPAN`, so a disabled tracer allocates no Span objects, no
attr dicts, and no traceparent bytes.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import os
import random
import time
from contextlib import contextmanager

logger = logging.getLogger("garage.tracing")

# span/trace ids only need uniqueness, not unpredictability; a seeded
# PRNG avoids two getrandom() syscalls per span on the hot path (the
# flight recorder keeps span creation on by default)
_ids = random.Random(int.from_bytes(os.urandom(16), "big") ^ os.getpid())

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "garage_current_span", default=None
)

MAX_BUFFER = 8192
FLUSH_INTERVAL = 3.0

TRACEPARENT_LEN = 16 + 8 + 1  # trace id + parent span id + flags
FLAG_SAMPLED = 0x01


class _NoopSpan:
    """Reusable, re-enterable no-op context manager: the disabled-tracing
    fast path.  Hot callers use `tracer.span(...) if tracer.enabled else
    NOOP_SPAN` so the disabled branch never builds span names or attrs."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


NOOP_SPAN = _NoopSpan()


class RemoteParent:
    """A parent span living on another node, reconstructed from a
    traceparent.  Duck-typed to Span for the two fields a child reads."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: bytes, span_id: bytes, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start_ns", "end_ns", "attrs", "ok",
    )

    def __init__(self, name: str, parent: "Span | RemoteParent | None", attrs: dict):
        self.name = name
        self.trace_id = (
            parent.trace_id if parent else _ids.getrandbits(128).to_bytes(16, "big")
        )
        self.span_id = _ids.getrandbits(64).to_bytes(8, "big")
        self.parent_id = parent.span_id if parent else None
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attrs = attrs
        self.ok = True


class Tracer:
    def __init__(self):
        self.sink: str | None = None
        self.service_name = "garage-tpu"
        self._buf: list[Span] = []
        self._task: asyncio.Task | None = None
        self._session = None
        # span-end hooks (utils/flight.py SlowRequestRecorder): attaching
        # one enables span creation even without an export sink, so the
        # flight recorder works with zero external collectors
        self._hooks: list = []

    @property
    def enabled(self) -> bool:
        return self.sink is not None or bool(self._hooks)

    def add_hook(self, fn) -> None:
        """Register fn(span), called once per finished span."""
        if fn not in self._hooks:
            self._hooks.append(fn)

    def remove_hook(self, fn) -> None:
        try:
            self._hooks.remove(fn)
        except ValueError:
            pass

    def configure(self, sink: str | None, service_name: str = "garage-tpu") -> None:
        self.sink = sink
        self.service_name = service_name
        if sink and self._task is None:
            try:
                self._task = asyncio.get_event_loop().create_task(self._flusher())
            except RuntimeError:
                pass  # no loop yet; caller may start() later

    async def start(self) -> None:
        if self.sink and (self._task is None or self._task.done()):
            self._task = asyncio.get_event_loop().create_task(self._flusher())

    async def stop(self) -> None:
        if self._task is not None:
            from .aio import reap

            await reap([self._task], log=logger, what="trace flusher")
            self._task = None
        await self._flush()
        if self._session is not None:
            await self._session.close()
            self._session = None

    @contextmanager
    def span(self, name: str, remote_parent: RemoteParent | None = None, **attrs):
        """Context manager for a traced operation.  Cheap no-op (no span
        object at all) when tracing is off.

        `remote_parent` (from `extract()`) parents the span across the
        wire.  When given it WINS over any context-inherited span: a
        handler task inherits the contextvars snapshot of the connection's
        recv loop (frozen at connection setup), so an in-context span
        there is stale; the traceparent the caller serialized is the
        truth.  On the local-dispatch shortcut both agree — the injected
        traceparent is the caller's current span."""
        if not self.enabled:
            yield None
            return
        parent = remote_parent or _current.get()
        s = Span(name, parent, attrs)
        token = _current.set(s)
        try:
            yield s
        except BaseException:
            s.ok = False
            raise
        finally:
            _current.reset(token)
            s.end_ns = time.time_ns()
            # export buffer fills only when a sink is configured; hooks
            # (flight recorder) see every span either way
            if self.sink is not None and len(self._buf) < MAX_BUFFER:
                self._buf.append(s)
            for hook in self._hooks:
                try:
                    hook(s)
                except Exception as e:  # noqa: BLE001 — hooks must not fail spans
                    logger.debug("span hook failed: %r", e)

    def current(self) -> Span | None:
        return _current.get()

    # --- cross-node propagation -----------------------------------------------

    def inject(self) -> bytes | None:
        """Serialize the current span for the wire: 16-byte trace id +
        8-byte span id + flags (W3C traceparent fields, binary).  None
        when tracing is off or no span is active — callers then omit the
        frame field entirely, keeping the disabled wire format identical."""
        if not self.enabled:
            return None
        s = _current.get()
        if s is None:
            return None
        return s.trace_id + s.span_id + bytes((FLAG_SAMPLED,))

    def extract(self, tp: bytes | None) -> RemoteParent | None:
        """Parse a traceparent produced by `inject()` on another node.
        Malformed or absent input yields None (the span becomes a local
        root — never an error: tracing must not fail requests)."""
        if not isinstance(tp, (bytes, bytearray)) or len(tp) != TRACEPARENT_LEN:
            return None
        tp = bytes(tp)
        return RemoteParent(tp[:16], tp[16:24], bool(tp[24] & FLAG_SAMPLED))

    # --- export ---------------------------------------------------------------

    async def _flusher(self) -> None:
        while True:
            await asyncio.sleep(FLUSH_INTERVAL)
            try:
                await self._flush()
            except Exception as e:  # noqa: BLE001 — tracing must never kill the daemon
                logger.debug("trace export failed: %r", e)

    async def _flush(self) -> None:
        if not self._buf or not self.sink:
            return
        spans, self._buf = self._buf, []
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        url = self.sink.rstrip("/") + "/v1/traces"
        # chunked export: one giant POST can exceed a collector's request
        # size limit (aiohttp servers default to 1 MiB) and lose the whole
        # batch; ~500 spans stays comfortably under typical limits
        for i in range(0, len(spans), 500):
            payload = self._otlp(spans[i : i + 500])
            async with self._session.post(
                url, json=payload, timeout=aiohttp.ClientTimeout(total=10)
            ) as resp:
                if resp.status >= 400:
                    logger.debug("trace sink returned %d", resp.status)

    def _otlp(self, spans: list[Span]) -> dict:
        """OTLP/HTTP JSON encoding (trace ids hex, times in ns strings)."""

        def attr(k, v):
            if isinstance(v, bool):
                val = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            return {"key": k, "value": val}

        return {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [attr("service.name", self.service_name)]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "garage-tpu"},
                            "spans": [
                                {
                                    "traceId": s.trace_id.hex(),
                                    "spanId": s.span_id.hex(),
                                    **(
                                        {"parentSpanId": s.parent_id.hex()}
                                        if s.parent_id
                                        else {}
                                    ),
                                    "name": s.name,
                                    "kind": 1,
                                    "startTimeUnixNano": str(s.start_ns),
                                    "endTimeUnixNano": str(s.end_ns),
                                    "attributes": [
                                        attr(k, v) for k, v in s.attrs.items()
                                    ],
                                    "status": {"code": 1 if s.ok else 2},
                                }
                                for s in spans
                            ],
                        }
                    ],
                }
            ]
        }


# process-wide tracer (configured by the daemon from admin.trace_sink)
tracer = Tracer()


def span(name: str, **attrs):
    return tracer.span(name, **attrs)
