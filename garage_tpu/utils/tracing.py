"""Span tracing with OTLP/HTTP export (reference: OpenTelemetry spans
around every RPC/API/table op, exported via OTLP when `admin.trace_sink`
is configured — src/garage/tracing_setup.rs:13-37, src/rpc/rpc_helper.rs:172-217).

Design: a contextvar carries the current span, so `with span("name"):`
nests correctly across asyncio task boundaries (contextvars propagate
into tasks).  Finished spans buffer in memory and a background flusher
POSTs them as OTLP/HTTP JSON (`/v1/traces`) to the sink.  When no sink is
configured the API is a near-zero-cost no-op — the hot paths stay hot.

Span ids follow W3C sizes: 16-byte trace id, 8-byte span id.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import os
import time
from contextlib import contextmanager

logger = logging.getLogger("garage.tracing")

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "garage_current_span", default=None
)

MAX_BUFFER = 8192
FLUSH_INTERVAL = 3.0


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start_ns", "end_ns", "attrs", "ok",
    )

    def __init__(self, name: str, parent: "Span | None", attrs: dict):
        self.name = name
        self.trace_id = parent.trace_id if parent else os.urandom(16)
        self.span_id = os.urandom(8)
        self.parent_id = parent.span_id if parent else None
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attrs = attrs
        self.ok = True


class Tracer:
    def __init__(self):
        self.sink: str | None = None
        self.service_name = "garage-tpu"
        self._buf: list[Span] = []
        self._task: asyncio.Task | None = None
        self._session = None

    @property
    def enabled(self) -> bool:
        return self.sink is not None

    def configure(self, sink: str | None, service_name: str = "garage-tpu") -> None:
        self.sink = sink
        self.service_name = service_name
        if sink and self._task is None:
            try:
                self._task = asyncio.get_event_loop().create_task(self._flusher())
            except RuntimeError:
                pass  # no loop yet; caller may start() later

    async def start(self) -> None:
        if self.sink and (self._task is None or self._task.done()):
            self._task = asyncio.get_event_loop().create_task(self._flusher())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        await self._flush()
        if self._session is not None:
            await self._session.close()
            self._session = None

    @contextmanager
    def span(self, name: str, **attrs):
        """Context manager for a traced operation.  Cheap no-op (no span
        object at all) when tracing is off."""
        if not self.enabled:
            yield None
            return
        parent = _current.get()
        s = Span(name, parent, attrs)
        token = _current.set(s)
        try:
            yield s
        except BaseException:
            s.ok = False
            raise
        finally:
            _current.reset(token)
            s.end_ns = time.time_ns()
            if len(self._buf) < MAX_BUFFER:
                self._buf.append(s)

    def current(self) -> Span | None:
        return _current.get()

    # --- export ---------------------------------------------------------------

    async def _flusher(self) -> None:
        while True:
            await asyncio.sleep(FLUSH_INTERVAL)
            try:
                await self._flush()
            except Exception as e:  # noqa: BLE001 — tracing must never kill the daemon
                logger.debug("trace export failed: %r", e)

    async def _flush(self) -> None:
        if not self._buf or not self.sink:
            return
        spans, self._buf = self._buf, []
        payload = self._otlp(spans)
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        url = self.sink.rstrip("/") + "/v1/traces"
        async with self._session.post(
            url, json=payload, timeout=aiohttp.ClientTimeout(total=10)
        ) as resp:
            if resp.status >= 400:
                logger.debug("trace sink returned %d", resp.status)

    def _otlp(self, spans: list[Span]) -> dict:
        """OTLP/HTTP JSON encoding (trace ids hex, times in ns strings)."""

        def attr(k, v):
            if isinstance(v, bool):
                val = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            return {"key": k, "value": val}

        return {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [attr("service.name", self.service_name)]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "garage-tpu"},
                            "spans": [
                                {
                                    "traceId": s.trace_id.hex(),
                                    "spanId": s.span_id.hex(),
                                    **(
                                        {"parentSpanId": s.parent_id.hex()}
                                        if s.parent_id
                                        else {}
                                    ),
                                    "name": s.name,
                                    "kind": 1,
                                    "startTimeUnixNano": str(s.start_ns),
                                    "endTimeUnixNano": str(s.end_ns),
                                    "attributes": [
                                        attr(k, v) for k, v in s.attrs.items()
                                    ],
                                    "status": {"code": 1 if s.ok else 2},
                                }
                                for s in spans
                            ],
                        }
                    ],
                }
            ]
        }


# process-wide tracer (configured by the daemon from admin.trace_sink)
tracer = Tracer()


def span(name: str, **attrs):
    return tracer.span(name, **attrs)
