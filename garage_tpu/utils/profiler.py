"""Sampling wall-clock profiler: attributed stacks from a live daemon.

Stdlib-only (the PR 7 flight-recorder discipline): a background thread
samples `sys._current_frames()` — every thread's live stack — plus the
asyncio task set at a configurable Hz, aggregating collapsed stacks in
flamegraph.pl / speedscope form.  Because the sampler is a *thread*, it
keeps sampling while the event loop is wedged — the wedge IS the
profile, which is exactly the ISSUE 17 point: event-loop starvation
("flakes under box load") stops being folklore and becomes attributed
stacks.

Three consumers:

  1. `profile(seconds, hz)` — on-demand runs behind admin
     `GET /v1/debug/profile?seconds=N` and `cli ... debug profile`.
  2. `SamplingProfiler` — the raw engine, also usable synchronously
     from a non-loop thread (the stall auto-capture path).
  3. `StallProfiler` — the opt-in `[admin] stall_profile` hook: when the
     event-loop watchdog (utils/flight.py) detects a stall it calls
     `on_stall(...)` from its MONITOR thread; a short burst of samples
     is captured right there (the wedged loop cannot help) and the top
     stacks ride a flight-recorder event (`loop-stall-profile`), so
     every `event_loop_blocked_total` increment leaves evidence.

The thread that owns the event loop is tagged `[event-loop]` in its
stack root: a profile whose event-loop thread spends its samples inside
codec math or zstd instead of `select()` is the starved-loop signature
(doc/monitoring.md §"Codec X-ray" runbook).

This module grew out of utils/flight.py, which re-exports the profiler
names unchanged — existing `flight.profile(...)` callers keep working.
"""

from __future__ import annotations

import asyncio
import collections
import sys
import threading
import time

# --- stack formatting helpers -------------------------------------------------


def _format_frame(frame) -> str:
    code = frame.f_code
    path = code.co_filename.replace("\\", "/").split("/")
    short = "/".join(path[-2:])
    # ';' is the folded-stack separator — keep it out of frame names
    name = code.co_name.replace(";", ",")
    return f"{name} ({short}:{frame.f_lineno})"


def _thread_stack(frame) -> list[str]:
    """Leaf frame -> root-first formatted stack."""
    out: list[str] = []
    while frame is not None:
        out.append(_format_frame(frame))
        frame = frame.f_back
    out.reverse()
    return out


def _task_frames(task) -> list:
    """Outermost-first suspended frames of an asyncio task, walking the
    cr_await chain.  Empty for a currently-RUNNING task (its frames show
    up in `sys._current_frames()` instead)."""
    frames = []
    coro = task.get_coro()
    seen = 0
    while coro is not None and seen < 64:
        seen += 1
        fr = getattr(coro, "cr_frame", None) or getattr(coro, "gi_frame", None)
        if fr is None:
            break  # running (or closed): the thread sampler owns it
        frames.append(fr)
        coro = getattr(coro, "cr_await", None) or getattr(coro, "gi_yieldfrom", None)
    return frames


def _task_label(task) -> str:
    coro = task.get_coro()
    name = getattr(coro, "__qualname__", None) or task.get_name()
    return f"task:{name}".replace(";", ",")


def _all_tasks(loop) -> set:
    """asyncio.all_tasks from another thread: the WeakSet can mutate
    mid-iteration on a live loop; retry a few times, give up quietly
    (a wedged loop — the interesting case — cannot mutate it)."""
    for _ in range(4):
        try:
            return asyncio.all_tasks(loop)
        except RuntimeError:
            continue
        # graft-lint: allow-swallow(diagnostics must never raise; sampler gives up quietly)
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            break
    return set()


# --- sampling profiler --------------------------------------------------------


class ProfileResult:
    """Aggregated collapsed stacks from one profiling run."""

    def __init__(self, hz: int):
        self.hz = hz
        self.samples = 0  # sampling rounds completed
        self.stacks: collections.Counter = collections.Counter()

    def add(self, stack: tuple[str, ...]) -> None:
        self.stacks[stack] += 1

    def folded(self) -> str:
        """flamegraph.pl / speedscope folded-stack text, hottest first."""
        lines = [
            f"{';'.join(stack)} {count}"
            for stack, count in sorted(
                self.stacks.items(), key=lambda kv: -kv[1]
            )
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def top_stacks(self, n: int = 5) -> list[str]:
        """The n hottest collapsed stacks, "frames... count" form — the
        payload the stall auto-capture event carries (bounded: a flight
        record must stay a log line, not a flamegraph)."""
        return [
            f"{';'.join(stack)} {count}"
            for stack, count in sorted(
                self.stacks.items(), key=lambda kv: -kv[1]
            )[:n]
        ]

    def speedscope(self) -> dict:
        """speedscope 'sampled' profile (https://www.speedscope.app)."""
        frame_index: dict[str, int] = {}
        samples: list[list[int]] = []
        weights: list[int] = []
        for stack, count in self.stacks.items():
            samples.append(
                [frame_index.setdefault(f, len(frame_index)) for f in stack]
            )
            weights.append(count)
        total = sum(weights)
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "name": "garage-tpu profile",
            "exporter": "garage-tpu flight recorder",
            "activeProfileIndex": 0,
            "shared": {"frames": [{"name": f} for f in frame_index]},
            "profiles": [
                {
                    "type": "sampled",
                    "name": f"{self.samples} rounds @ {self.hz} Hz",
                    "unit": "none",
                    "startValue": 0,
                    "endValue": total,
                    "samples": samples,
                    "weights": weights,
                }
            ],
        }


class SamplingProfiler:
    """One profiling run: a daemon thread sampling thread stacks + the
    asyncio task set at `hz` until the deadline.  `loop_ident` (the
    thread id that runs the event loop) tags that thread's stack root
    with `[event-loop]` so loop starvation is visible at a glance."""

    def __init__(self, loop, hz: int = 100, loop_ident: int | None = None):
        self.loop = loop
        self.loop_ident = loop_ident
        self.result = ProfileResult(hz)
        self._stop = False
        self._own_ident: int | None = None

    def run(self, seconds: float) -> None:
        self._own_ident = threading.get_ident()
        interval = 1.0 / self.result.hz
        deadline = time.monotonic() + seconds
        while not self._stop and time.monotonic() < deadline:
            self._sample()
            time.sleep(interval)

    def stop(self) -> None:
        self._stop = True

    def _sample(self) -> None:
        res = self.result
        res.samples += 1
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            if tid == self._own_ident:
                continue
            root = "thread:" + names.get(tid, str(tid)).replace(";", ",")
            if tid == self.loop_ident:
                root += " [event-loop]"
            res.add(tuple([root] + _thread_stack(frame)))
        # suspended asyncio tasks: where is everything parked?
        for task in _all_tasks(self.loop):
            try:
                frames = _task_frames(task)
            # graft-lint: allow-swallow(profiler samples at ~100 Hz; a vanished task is not news)
            except Exception:  # noqa: BLE001
                continue
            if not frames:
                continue  # running task, covered by the thread sample
            res.add(
                tuple([_task_label(task)] + [_format_frame(f) for f in frames])
            )


async def profile(seconds: float, hz: int = 100, loop=None) -> ProfileResult:
    """Profile this process for `seconds` without blocking the loop.
    Inputs are coerced and clamped here (seconds 0.05..60, hz 1..1000)
    so the admin HTTP and RPC front-ends share one bounds policy."""
    seconds = min(max(float(seconds), 0.05), 60.0)
    running = asyncio.get_running_loop()
    loop = loop or running
    # the awaiting thread IS the loop thread when profiling ourselves —
    # that ident gets the [event-loop] root tag
    loop_ident = threading.get_ident() if loop is running else None
    prof = SamplingProfiler(
        loop, hz=max(1, min(int(hz), 1000)), loop_ident=loop_ident
    )
    t = threading.Thread(
        target=prof.run, args=(float(seconds),),
        name="garage-profiler", daemon=True,
    )
    t.start()
    try:
        while t.is_alive():
            await asyncio.sleep(0.02)
    finally:
        prof.stop()
        t.join(timeout=2.0)
    return prof.result


# --- stall auto-capture -------------------------------------------------------


class StallProfiler:
    """Opt-in bridge from the event-loop watchdog to the profiler
    (`[admin] stall_profile = true`): every counted stall episode
    captures a short synchronous sample burst and records a
    `loop-stall-profile` flight event carrying the top stacks.

    `on_stall` runs on the WATCHDOG MONITOR THREAD while the loop is
    still wedged — the only moment the culprit is on-stack — so the
    burst is sampled inline (no thread spawn mid-incident), bounded by
    `seconds`, and rate-limited by `min_interval` (a loop thrashing in
    and out of stalls must not turn the profiler into the load)."""

    def __init__(
        self,
        seconds: float = 0.25,
        hz: int = 50,
        top: int = 5,
        min_interval: float = 30.0,
    ):
        self.seconds = float(seconds)
        self.hz = int(hz)
        self.top = int(top)
        self.min_interval = float(min_interval)
        self.captures = 0
        self._last = 0.0

    def on_stall(self, overdue: float, loop=None, loop_ident=None) -> None:
        now = time.monotonic()
        if now - self._last < self.min_interval:
            return
        self._last = now
        try:
            prof = SamplingProfiler(loop, hz=self.hz, loop_ident=loop_ident)
            prof.run(self.seconds)  # synchronous: already off-loop
            res = prof.result
            self.captures += 1
            from .flight import record_event

            record_event(
                "loop-stall-profile",
                {
                    "overdueMs": round(overdue * 1000, 1),
                    "samples": res.samples,
                    "hz": res.hz,
                    "topStacks": "\n".join(res.top_stacks(self.top)),
                },
                severity="warn",
            )
        # graft-lint: allow-swallow(stall diagnostics must never take the watchdog thread down)
        except Exception:  # noqa: BLE001
            return
