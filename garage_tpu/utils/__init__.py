from .data import (
    FixedBytes32,
    Hash,
    Uuid,
    blake2sum,
    gen_uuid,
    hex_of,
    parse_hex,
    sha256sum,
)
from .error import Error, OkOrMessage
from .time_util import increment_logical_clock, msec_to_rfc3339, now_msec

__all__ = [
    "FixedBytes32",
    "Hash",
    "Uuid",
    "blake2sum",
    "gen_uuid",
    "hex_of",
    "parse_hex",
    "sha256sum",
    "Error",
    "OkOrMessage",
    "now_msec",
    "increment_logical_clock",
    "msec_to_rfc3339",
]
