"""Persistent XLA compilation cache (round-4, VERDICT.md Missing #1).

Every fresh bench/verify process used to pay the full Pallas/Mosaic compile
inside its kill budget — the round-3 `--hash 2048` dial died exactly there.
This module points JAX's persistent compilation cache at a committed-path
directory inside the repo, so:

- the FIRST healthy tunnel window pays compile once and writes the cache;
- every later process (including the driver's bench run) loads the compiled
  executable in milliseconds and spends its budget *executing*.

Cache entries are keyed by jax version + backend fingerprint + HLO, so they
are valid across processes on the same box/chip — exactly the driver's
situation.  The background banker (`script/tpu_bank.py`) git-commits
`.xla_cache/` together with each banked window's artifacts; until a healthy
window populates it, the directory is empty and every entry is a miss
(stale entries are also just misses, never wrong results).

The cache is only enabled on NON-CPU backends: CPU compiles are cheap and
can't wedge, and CPU-routed probes/fallback children used to accrete
CPU-backend entries into the committed accelerator cache, bloating every
artifact commit for zero benefit.  `enable_persistent_cache` is therefore
a no-op (returns "") when the process resolves to the CPU backend.

Reference analog: none (the reference is interpreted Rust; its hot loops
don't have a compile step).  This is TPU-operational plumbing.
"""

from __future__ import annotations

import functools
import os
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".xla_cache")

_enabled = False


def record_cache_event(cache: str, hit: bool) -> None:
    """Count a compile-cache lookup in the metrics registry
    (`tpu_compile_cache_{hit,miss}_total{cache=...}`) — the observability
    answer to five rounds of silent wedges: a miss storm on the bench
    path is visible on /metrics instead of buried in a JSON artifact."""
    from .metrics import registry

    registry.incr(
        "tpu_compile_cache_hit_total" if hit else "tpu_compile_cache_miss_total",
        (("cache", cache),),
    )


def record_compile_secs(cache: str, secs: float) -> None:
    """One compile event's wall seconds into `tpu_compile_duration{cache}`
    (histogram count = compile events, sum = total lowering seconds —
    the Codec X-ray's compile budget, doc/monitoring.md §"Codec X-ray").
    A cache HIT must never reach here: hits record no compile time, and
    tests/test_codec_xray.py asserts exactly that."""
    from .metrics import registry

    registry.observe("tpu_compile_duration", (("cache", cache),), secs)


def instrumented_cache(cache_name: str):
    """lru_cache-style memoizer that counts hits/misses per family AND
    times the miss path as a compile event.

    Used for the in-process jit/trace caches (ec kernels, blake3
    hashers): a process that keeps missing these is recompiling — exactly
    the wedge mode the persistent cache exists to kill, now measurable
    both as a count (miss storm) and as wall seconds lost."""

    def deco(fn):
        memo: dict = {}

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            key = (args, tuple(sorted(kwargs.items())))
            hit = key in memo
            record_cache_event(cache_name, hit)
            if not hit:
                t0 = time.perf_counter()
                memo[key] = fn(*args, **kwargs)
                record_compile_secs(cache_name, time.perf_counter() - t0)
            return memo[key]

        wrapper.cache_clear = memo.clear  # type: ignore[attr-defined]
        return wrapper

    return deco


def enable_persistent_cache(path: str | None = None) -> str:
    """Idempotently enable the persistent compilation cache.

    Must be called before (or after — jax.config is live) the first jit
    compile to have effect on it.  Returns the cache dir in use, or ""
    when disabled (CPU backend: see module docstring).
    """
    global _enabled
    path = path or os.environ.get("GARAGE_XLA_CACHE_DIR", DEFAULT_CACHE_DIR)
    if _enabled:
        return path
    # cheap env check first: CPU-pinned children (bench.py cpu_env, the
    # test suite) never initialize a backend just to learn it's cpu
    # graft-lint: allow-backend-gate(pre-jax-import probe: routing through ops.telemetry would initialize the backend this check exists to avoid)
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return ""

    import jax

    # graft-lint: allow-backend-gate(CPU cache opt-out is the documented design of this module; the resolved backend is the probe result itself)
    if jax.default_backend() == "cpu":
        return ""
    os.makedirs(path, exist_ok=True)

    jax.config.update("jax_compilation_cache_dir", path)
    # scrape-time view of the persistent cache: entry count says whether
    # a window has ever banked compiled executables for this backend
    from .metrics import registry

    registry.register_gauge(
        "xla_persistent_cache_entries", (),
        lambda: sum(1 for f in os.listdir(path) if not f.startswith(".")),
    )
    # Cache EVERYTHING: the default thresholds skip small/fast compiles,
    # but on the tunneled backend even "fast" remote compiles can wedge —
    # a cache hit skips the remote round-trip entirely.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update(
            "jax_persistent_cache_enable_xla_caches",
            "all",
        )
    # graft-lint: allow-swallow(older jax lacks the flag; core cache still works)
    except Exception:  # older jax: flag absent — core cache still works
        pass
    _enabled = True
    return path
