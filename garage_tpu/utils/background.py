"""Background worker runtime.

Mirrors reference src/util/background/ (mod.rs:16, worker.rs:41-59): workers
implement `work()` (one unit, returns its next state) and `wait_for_work()`
(sleep until something to do); a supervisor tracks per-worker state, last
error, and consecutive-error count, applying exponential backoff after
failures (worker.rs:188-232).  `BgVars` are runtime-tunable knobs exposed via
the `worker set`/`worker get` CLI (src/util/background/vars.rs).

asyncio-native: each worker is a task; the runner owns cancellation with an
exit deadline (reference worker.rs:19 — 8 s).
"""

from __future__ import annotations

import asyncio
import enum
import logging
import traceback
from typing import Any, Callable

logger = logging.getLogger("garage.background")

EXIT_DEADLINE_SEC = 8.0

# The event loop only keeps weak references to tasks; fire-and-forget tasks
# must be anchored somewhere or they can be garbage-collected mid-flight.
_background_tasks: set[asyncio.Task] = set()


def spawn(coro, name: str | None = None) -> asyncio.Task:
    """create_task with a strong reference held until completion."""
    t = asyncio.create_task(coro, name=name)
    _background_tasks.add(t)
    t.add_done_callback(_background_tasks.discard)
    return t


class WorkerState(enum.Enum):
    BUSY = "busy"  # did work, call work() again immediately
    THROTTLED = "throttled"  # busy but wait a given delay (value set aside)
    IDLE = "idle"  # call wait_for_work()
    DONE = "done"  # worker finished, exit


class Worker:
    """Subclass and override name/work/wait_for_work."""

    def name(self) -> str:
        return type(self).__name__

    def status(self) -> dict[str, Any]:
        """Freeform progress info for `worker info` (reference WorkerStatus)."""
        return {}

    async def work(self) -> WorkerState | tuple[WorkerState, float]:
        """Do one unit of work.  Return THROTTLED with a delay as
        (WorkerState.THROTTLED, seconds) to self-throttle."""
        raise NotImplementedError

    async def wait_for_work(self) -> None:
        """Sleep until there may be work; default polls every second."""
        await asyncio.sleep(1.0)


class WorkerInfo:
    def __init__(self, name: str):
        self.name = name
        self.state: str = "idle"
        self.errors = 0
        self.consecutive_errors = 0
        self.last_error: str | None = None
        self.tranquility: int | None = None
        self.progress: dict[str, Any] = {}


class BackgroundRunner:
    """Spawns and supervises workers (reference src/util/background/mod.rs)."""

    def __init__(self) -> None:
        self.workers: dict[int, tuple[Worker, WorkerInfo, asyncio.Task]] = {}
        self._next_id = 1
        self._stopping = False

    def spawn(self, worker: Worker) -> int:
        wid = self._next_id
        self._next_id += 1
        info = WorkerInfo(worker.name())
        task = asyncio.create_task(self._run_worker(worker, info), name=worker.name())
        self.workers[wid] = (worker, info, task)
        return wid

    async def _run_worker(self, worker: Worker, info: WorkerInfo) -> None:
        while not self._stopping:
            try:
                res = await worker.work()
                info.consecutive_errors = 0
                if isinstance(res, tuple):
                    state, delay = res
                else:
                    state, delay = res, 0.0
                info.state = state.value
                info.progress = worker.status()
                if state == WorkerState.DONE:
                    return
                if state == WorkerState.THROTTLED and delay > 0:
                    await asyncio.sleep(delay)
                elif state == WorkerState.IDLE:
                    try:
                        await asyncio.wait_for(worker.wait_for_work(), timeout=30.0)
                    except asyncio.TimeoutError:
                        pass
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — supervisor must survive
                info.errors += 1
                info.consecutive_errors += 1
                info.last_error = f"{e!r}"
                logger.warning(
                    "worker %s error: %s\n%s", info.name, e, traceback.format_exc()
                )
                # exponential backoff, capped (reference worker.rs:188-232)
                await asyncio.sleep(min(60.0, 2.0 ** min(info.consecutive_errors, 6)))

    def worker_info(self) -> dict[int, WorkerInfo]:
        return {wid: info for wid, (_w, info, _t) in self.workers.items()}

    async def shutdown(self) -> None:
        self._stopping = True
        tasks = [t for (_w, _i, t) in self.workers.values()]
        for t in tasks:
            t.cancel()
        if tasks:
            done, pending = await asyncio.wait(tasks, timeout=EXIT_DEADLINE_SEC)
            for t in pending:
                logger.warning("worker %s did not exit before deadline", t.get_name())


class BgVars:
    """Runtime-mutable named variables with getter/setter hooks
    (reference src/util/background/vars.rs)."""

    def __init__(self) -> None:
        self._vars: dict[str, tuple[Callable[[], str], Callable[[str], None]]] = {}

    def register_rw(
        self, name: str, get: Callable[[], str], set_: Callable[[str], None]
    ) -> None:
        self._vars[name] = (get, set_)

    def get(self, name: str) -> str:
        if name not in self._vars:
            raise KeyError(f"unknown variable {name!r}")
        return self._vars[name][0]()

    def set(self, name: str, value: str) -> None:
        if name not in self._vars:
            raise KeyError(f"unknown variable {name!r}")
        self._vars[name][1](value)

    def all(self) -> dict[str, str]:
        return {k: g() for k, (g, _s) in sorted(self._vars.items())}
