"""Background worker runtime.

Mirrors reference src/util/background/ (mod.rs:16, worker.rs:41-59): workers
implement `work()` (one unit, returns its next state) and `wait_for_work()`
(sleep until something to do); a supervisor tracks per-worker state, last
error, and consecutive-error count, applying exponential backoff after
failures (worker.rs:188-232).  `BgVars` are runtime-tunable knobs exposed via
the `worker set`/`worker get` CLI (src/util/background/vars.rs).

asyncio-native: each worker is a task; the runner owns cancellation with an
exit deadline (reference worker.rs:19 — 8 s).
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import logging
import time
import traceback
from typing import Any, Callable

logger = logging.getLogger("garage.background")

EXIT_DEADLINE_SEC = 8.0

# EWMA smoothing for per-worker iteration duration / throughput
EWMA_ALPHA = 0.25

# worker_state gauge encoding
_STATE_NUM = {"idle": 0, "busy": 1, "throttled": 2, "done": 3}

# gauge `id` label source: PROCESS-wide, not per-runner.  The metrics
# registry is a process-global singleton and tests run several in-process
# Garage nodes — per-runner ids would collide ((name, labels) keys would
# overwrite each other, and one node's shutdown would delete the others'
# worker families).
_gauge_ids = itertools.count(1)

def spawn(coro, name: str | None = None) -> asyncio.Task:
    """create_task with a strong reference held until completion and
    crash logging — delegates to the shared supervised-spawn registry
    (utils/aio.py), kept as an alias for its existing call sites."""
    from .aio import spawn_supervised

    return spawn_supervised(coro, name=name)


class WorkerState(enum.Enum):
    BUSY = "busy"  # did work, call work() again immediately
    THROTTLED = "throttled"  # busy but wait a given delay (value set aside)
    IDLE = "idle"  # call wait_for_work()
    DONE = "done"  # worker finished, exit


class Worker:
    """Subclass and override name/work/wait_for_work."""

    def name(self) -> str:
        return type(self).__name__

    def status(self) -> dict[str, Any]:
        """Freeform progress info for `worker info` (reference WorkerStatus)."""
        return {}

    async def work(self) -> WorkerState | tuple[WorkerState, float]:
        """Do one unit of work.  Return THROTTLED with a delay as
        (WorkerState.THROTTLED, seconds) to self-throttle."""
        raise NotImplementedError

    async def wait_for_work(self) -> None:
        """Sleep until there may be work; default polls every second."""
        await asyncio.sleep(1.0)

    def tranquility(self) -> int | None:
        """Current tranquility setting, for workers that have one
        (resync, scrub) — shown in `worker list`."""
        return None

    def queue_length(self) -> int | None:
        """Backlog behind this worker, if it drains one — exported as
        `worker_queue_length{worker=...}`.  The default recognizes the
        conventional status() keys; override for anything else."""
        st = self.status()
        for k in ("queue", "todo", "queued"):
            v = st.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return int(v)
        return None


class WorkerInfo:
    def __init__(self, name: str):
        self.name = name
        self.state: str = "idle"
        self.errors = 0
        self.consecutive_errors = 0
        self.last_error: str | None = None
        self.tranquility: int | None = None
        self.progress: dict[str, Any] = {}
        # per-iteration runtime stats (reference WorkerStatus deepening)
        self.iterations = 0
        self.last_duration_secs: float | None = None
        self.duration_ewma_secs: float | None = None
        self.throughput: float | None = None  # work() completions / sec, EWMA
        self.last_completed: float | None = None  # unix timestamp
        self._last_mono: float | None = None

    def note_iteration(self, duration: float) -> None:
        """Record one completed work() call (success or error)."""
        now_mono = time.monotonic()
        self.iterations += 1
        self.last_duration_secs = duration
        self.duration_ewma_secs = (
            duration
            if self.duration_ewma_secs is None
            else EWMA_ALPHA * duration + (1 - EWMA_ALPHA) * self.duration_ewma_secs
        )
        if self._last_mono is not None:
            gap = max(now_mono - self._last_mono, 1e-9)
            rate = 1.0 / gap
            self.throughput = (
                rate
                if self.throughput is None
                else EWMA_ALPHA * rate + (1 - EWMA_ALPHA) * self.throughput
            )
        self._last_mono = now_mono
        self.last_completed = time.time()


class BackgroundRunner:
    """Spawns and supervises workers (reference src/util/background/mod.rs)."""

    def __init__(self) -> None:
        self.workers: dict[int, tuple[Worker, WorkerInfo, asyncio.Task]] = {}
        self._next_id = 1
        self._stopping = False
        self._gauge_keys: dict[int, list[tuple]] = {}

    def spawn(self, worker: Worker) -> int:
        wid = self._next_id
        self._next_id += 1
        info = WorkerInfo(worker.name())
        self._register_worker_gauges(wid, worker, info)
        task = asyncio.create_task(
            self._run_worker(wid, worker, info), name=worker.name()
        )
        self.workers[wid] = (worker, info, task)
        return wid

    def _register_worker_gauges(self, wid: int, worker: Worker, info: WorkerInfo):
        """Registry-backed per-worker health families (replaces the old
        bare inline `worker_errors` gauge): errors, state, throughput,
        and queue length where the worker exposes one.  The `id` label
        keeps labelsets unique across same-named workers (a repair
        launched twice, or several in-process nodes) — it is a process-
        wide spawn sequence, not the per-runner `worker list` id."""
        from .metrics import registry

        lbl = (("worker", info.name), ("id", str(next(_gauge_ids))))
        keys = self._gauge_keys[wid] = []

        def reg(name, fn):
            registry.register_gauge(name, lbl, fn)
            keys.append((name, lbl))

        reg("worker_errors_total", lambda i=info: i.errors)
        reg("worker_state", lambda i=info: _STATE_NUM.get(i.state, -1))
        # fn raising on None drops the sample at scrape time
        reg("worker_throughput", lambda i=info: float(i.throughput))
        reg("worker_queue_length", lambda w=worker: int(w.queue_length()))

    def _unregister_worker_gauges(self, wid: int) -> None:
        from .metrics import registry

        for name, labels in self._gauge_keys.pop(wid, []):
            registry.unregister_gauge(name, labels)

    async def _run_worker(self, wid: int, worker: Worker, info: WorkerInfo) -> None:
        try:
            await self._work_loop(worker, info)
        finally:
            # a finished/cancelled worker must not keep exporting gauges
            # (each `repair` launch spawns fresh workers — without this,
            # a long-lived daemon accumulates dead-worker families and
            # pins the Worker objects via the gauge closures)
            self._unregister_worker_gauges(wid)

    async def _work_loop(self, worker: Worker, info: WorkerInfo) -> None:
        while not self._stopping:
            try:
                # time work() alone: status()/wait_for_work() must not
                # pollute the duration/throughput stats (an exception out
                # of a 30 s idle wait is not a 30 s work unit)
                t0 = time.perf_counter()
                try:
                    res = await worker.work()
                finally:
                    info.note_iteration(time.perf_counter() - t0)
                info.consecutive_errors = 0
                if isinstance(res, tuple):
                    state, delay = res
                else:
                    state, delay = res, 0.0
                info.state = state.value
                info.progress = worker.status()
                info.tranquility = worker.tranquility()
                if state == WorkerState.DONE:
                    return
                if state == WorkerState.THROTTLED and delay > 0:
                    await asyncio.sleep(delay)
                elif state == WorkerState.IDLE:
                    try:
                        await asyncio.wait_for(worker.wait_for_work(), timeout=30.0)
                    except asyncio.TimeoutError:
                        pass
            except asyncio.CancelledError:
                # shutdown cancelled us: end *cancelled* (not "done") so
                # reap/wait-side accounting sees a cancelled worker; the
                # runner's finally still unregisters the gauges
                raise
            except Exception as e:  # noqa: BLE001 — supervisor must survive
                info.errors += 1
                info.consecutive_errors += 1
                info.last_error = f"{e!r}"
                logger.warning(
                    "worker %s error: %s\n%s", info.name, e, traceback.format_exc()
                )
                # exponential backoff, capped (reference worker.rs:188-232)
                await asyncio.sleep(min(60.0, 2.0 ** min(info.consecutive_errors, 6)))

    def worker_info(self) -> dict[int, WorkerInfo]:
        return {wid: info for wid, (_w, info, _t) in self.workers.items()}

    async def shutdown(self) -> None:
        self._stopping = True
        tasks = [t for (_w, _i, t) in self.workers.values()]
        for t in tasks:
            t.cancel()
        if tasks:
            done, pending = await asyncio.wait(tasks, timeout=EXIT_DEADLINE_SEC)
            for t in pending:
                logger.warning("worker %s did not exit before deadline", t.get_name())
        # per-worker gauges are removed by each _run_worker's finally;
        # sweep whatever remains (tasks that missed the exit deadline)
        for wid in list(self._gauge_keys):
            self._unregister_worker_gauges(wid)


class BgVars:
    """Runtime-mutable named variables with getter/setter hooks
    (reference src/util/background/vars.rs)."""

    def __init__(self) -> None:
        self._vars: dict[str, tuple[Callable[[], str], Callable[[str], None]]] = {}

    def register_rw(
        self, name: str, get: Callable[[], str], set_: Callable[[str], None]
    ) -> None:
        self._vars[name] = (get, set_)

    def get(self, name: str) -> str:
        if name not in self._vars:
            raise KeyError(f"unknown variable {name!r}")
        return self._vars[name][0]()

    def set(self, name: str, value: str) -> None:
        if name not in self._vars:
            raise KeyError(f"unknown variable {name!r}")
        self._vars[name][1](value)

    def all(self) -> dict[str, str]:
        out = {}
        for k, (g, _s) in sorted(self._vars.items()):
            try:
                out[k] = g()
            except Exception as e:  # noqa: BLE001 — one dead var must not hide the rest
                out[k] = f"(unavailable: {e})"
        return out
