"""Flight recorder: node-local self-diagnostics.

Three coordinated tools that answer "why is this node slow?" from a
RUNNING daemon, with zero external collectors attached (the
`/debug/pprof` plane every production store grows; reference Garage
leans on tokio-console + metrics for the same questions):

  1. **Sampling profiler** — `profile(seconds, hz)` spawns a thread
     that samples `sys._current_frames()` (every thread's live stack)
     plus the asyncio task set at ~100 Hz, aggregates collapsed stacks,
     and renders them as folded-stack text (flamegraph.pl / speedscope
     paste format) or speedscope JSON.  Served from admin
     `GET /v1/debug/profile?seconds=N` and `cli ... debug profile`.
     Because the sampler is a *thread*, it keeps sampling even while
     the event loop is wedged — the wedge IS the profile.

  2. **Event-loop watchdog** — `EventLoopWatchdog` measures scheduling
     lag continuously (a self-rescheduling `call_later` beat feeds the
     `event_loop_lag_seconds` histogram) while a monitor thread detects
     stalls *in progress*: when the beat goes unserviced past the
     threshold it increments `event_loop_blocked_total`, samples the
     loop thread's current stack (the culprit, caught red-handed), and
     dumps every live asyncio task stack with its trace id (PR 2 log
     correlation) to the log, rate-limited.

  3. **Slow-request flight recorder** — `SlowRequestRecorder` hooks
     `utils/tracing.py` span end and retains the span trees of the
     slowest recent requests (threshold + top-K ring buffer), served
     from `GET /v1/debug/slow` and `cli ... debug slow`.  Attaching the
     hook enables span creation even without an OTLP sink, so "what was
     that p99" is answerable post-hoc on any node.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import sys
import threading
import time

from .metrics import registry

# the profiler grew into its own module (utils/profiler.py, ISSUE 17);
# re-exported here unchanged so existing flight.profile(...) callers —
# admin HTTP, admin RPC, CLI, tests — keep working
from .profiler import (  # noqa: F401 — re-exports are this module's API
    ProfileResult,
    SamplingProfiler,
    _all_tasks,
    _format_frame,
    _task_frames,
    _task_label,
    _thread_stack,
    profile,
)

logger = logging.getLogger("garage.flight")


def _task_trace_id(task) -> str:
    """Trace id of the span active in a task, '' when none.

    `Task.get_context()` only exists on 3.12+ and the 3.10/3.11 C task
    exposes no `_context` either, so fall back to scanning the await
    chain's frame locals: every tracing call site binds its span
    contextmanager to a local (`cm` in netapp/rpc_helper, `s` under
    `with ... as s`), which makes the active span recoverable from a
    suspended task on any supported interpreter."""
    try:
        from .tracing import Span, _current

        getctx = getattr(task, "get_context", None)
        ctx = getctx() if getctx is not None else getattr(task, "_context", None)
        if ctx is not None:
            span = ctx.get(_current)
            if span is not None:
                return span.trace_id.hex()
        for fr in reversed(_task_frames(task)):  # innermost first
            for v in fr.f_locals.values():
                if isinstance(v, Span):
                    return v.trace_id.hex()
                # _GeneratorContextManager from tracer.span(): the Span
                # lives in the suspended generator frame as `s`
                gen_frame = getattr(getattr(v, "gen", None), "gi_frame", None)
                if gen_frame is not None:
                    s = gen_frame.f_locals.get("s")
                    if isinstance(s, Span):
                        return s.trace_id.hex()
        return ""
    # graft-lint: allow-swallow(best-effort trace-id recovery from frame locals)
    except Exception:  # noqa: BLE001
        return ""


# --- event-loop watchdog ------------------------------------------------------


class EventLoopWatchdog:
    """Continuous event-loop scheduling-lag monitor + stall detector.

    Loop side: a self-rescheduling `call_later(tick)` beat observes its
    own lag into the `event_loop_lag_seconds` histogram.  Thread side: a
    monitor wakes every `tick` and, when the beat is overdue by more
    than `threshold`, counts a stall (`event_loop_blocked_total`, once
    per episode) and dumps the loop thread's current stack plus every
    live asyncio task stack — while the loop is still wedged, which is
    the only moment the culprit is on-stack."""

    def __init__(
        self,
        threshold: float = 0.25,
        tick: float = 0.1,
        dump_interval: float = 30.0,
    ):
        self.threshold = float(threshold)
        self.tick = float(tick)
        self.dump_interval = float(dump_interval)
        # optional stall hook (utils/profiler.StallProfiler.on_stall when
        # `[admin] stall_profile` is on): called once per counted episode,
        # FROM THE MONITOR THREAD, while the loop is still wedged
        self.on_stall = None
        self._loop = None
        self._loop_ident: int | None = None
        self._handle = None
        self._thread: threading.Thread | None = None
        self._stopped = False
        self._stalled = False
        self._last_beat = 0.0
        self._expected = 0.0
        self._last_dump = 0.0

    def start(self, loop=None) -> None:
        self._loop = loop or asyncio.get_event_loop()
        self._loop_ident = threading.get_ident()
        now = time.monotonic()
        self._last_beat = now
        self._expected = now + self.tick
        self._handle = self._loop.call_later(self.tick, self._beat)
        self._thread = threading.Thread(
            target=self._monitor, name="garage-loop-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped = True
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    # --- loop side: lag histogram --------------------------------------------

    def _beat(self) -> None:
        now = time.monotonic()
        lag = max(0.0, now - self._expected)
        registry.observe("event_loop_lag_seconds", (), lag)
        self._last_beat = now
        self._expected = now + self.tick
        if not self._stopped:
            self._handle = self._loop.call_later(self.tick, self._beat)

    # --- thread side: stall detection ----------------------------------------

    def _monitor(self) -> None:
        while not self._stopped:
            time.sleep(self.tick)
            overdue = time.monotonic() - self._last_beat - self.tick
            if overdue > self.threshold:
                if not self._stalled:
                    self._stalled = True
                    registry.incr("event_loop_blocked_total", ())
                    self._report(overdue)
                    if self.on_stall is not None:
                        try:
                            self.on_stall(overdue, self._loop, self._loop_ident)
                        # graft-lint: allow-swallow(stall diagnostics must never take the watchdog thread down)
                        except Exception:  # noqa: BLE001
                            pass
            else:
                self._stalled = False

    def _report(self, overdue: float) -> None:
        now = time.monotonic()
        if now - self._last_dump < self.dump_interval:
            logger.warning(
                "event loop blocked for %.0f ms (threshold %.0f ms); "
                "task dump suppressed (rate limit)",
                overdue * 1000, self.threshold * 1000,
            )
            return
        self._last_dump = now
        parts = [
            f"event loop blocked for {overdue * 1000:.0f} ms "
            f"(threshold {self.threshold * 1000:.0f} ms)"
        ]
        culprit = sys._current_frames().get(self._loop_ident)
        if culprit is not None:
            parts.append("blocked in (loop thread stack, innermost last):")
            parts.extend("    " + f for f in _thread_stack(culprit))
        tasks = _all_tasks(self._loop)
        parts.append(f"live asyncio tasks ({len(tasks)}):")
        for task in tasks:
            try:
                frames = _task_frames(task)
                tid = _task_trace_id(task)
                where = " <- ".join(
                    _format_frame(f) for f in reversed(frames)
                ) or "(running)"
                parts.append(
                    f"    {task.get_name()}"
                    + (f" trace={tid}" if tid else "")
                    + f": {where}"
                )
            # graft-lint: allow-swallow(task-dump is best-effort diagnostics mid-stall)
            except Exception:  # noqa: BLE001
                continue
        logger.warning("%s", "\n".join(parts))


# --- slow-request flight recorder ---------------------------------------------


class SlowRequestRecorder:
    """Bounded ring buffer of the span trees of recent slow requests.

    Registered as a tracer span-end hook (which by itself enables span
    creation — no OTLP sink needed).  Spans buffer per trace id; when a
    local root ends (no parent: the API request span on the serving
    node, or a manually-opened root), its subtree is extracted and, if
    the root exceeded `threshold_ms`, retained in a `top_k`-deep ring
    (most recent K slow requests; `snapshot()` orders by duration).
    Orphan trees — e.g. `rpc-handle:*` subtrees on a remote node whose
    root lives on the gateway — finalize via the expiry sweep instead."""

    SWEEP_EVERY = 512  # hook calls between pending-expiry sweeps
    MAX_PENDING_TRACES = 1024
    MAX_SPANS_PER_TRACE = 512
    PENDING_TTL = 30.0  # seconds a parentless subtree may linger

    # flight events retained for the federated cluster timeline
    # (rpc/transition.py) — a dedicated ring so a burst of slow
    # requests cannot evict the durability alert an operator needs
    EVENTS_TOP_K = 256

    def __init__(self, threshold_ms: float = 500.0, top_k: int = 64):
        self.threshold_ms = float(threshold_ms)
        self.top_k = int(top_k)
        self.records: collections.deque = collections.deque(maxlen=self.top_k)
        self.events: collections.deque = collections.deque(
            maxlen=self.EVENTS_TOP_K
        )
        # trace id -> [last_touch_monotonic, [spans]]
        self.pending: dict[bytes, list] = {}
        self.dropped = 0  # spans discarded by the per-trace cap
        self._calls = 0

    # the tracer hook — called on the event loop for every finished span
    def on_span_end(self, span) -> None:
        self._calls += 1
        if self._calls % self.SWEEP_EVERY == 0:
            self._sweep()
        ent = self.pending.get(span.trace_id)
        if ent is None:
            if len(self.pending) >= self.MAX_PENDING_TRACES:
                # evict the oldest-inserted trace (dict order, O(1) — no
                # full scan on the hot path), finalizing it the same way
                # the TTL sweep would: a slow subtree must not vanish
                # just because the node is busy
                self._expire(next(iter(self.pending)))
            ent = self.pending[span.trace_id] = [time.monotonic(), []]
        ent[0] = time.monotonic()
        if len(ent[1]) < self.MAX_SPANS_PER_TRACE:
            ent[1].append(span)
        else:
            self.dropped += 1
        if span.parent_id is None:
            self._finalize(span)

    def _finalize(self, root) -> None:
        ent = self.pending.get(root.trace_id)
        if ent is None:
            return
        tree, rest = _extract_tree(root, ent[1])
        if rest:
            ent[1] = rest
        else:
            del self.pending[root.trace_id]
        self._maybe_record(root, tree)

    def _maybe_record(self, root, tree) -> None:
        duration_ms = (root.end_ns - root.start_ns) / 1e6
        if duration_ms < self.threshold_ms:
            return
        self.records.append(_build_record(root, tree, duration_ms))

    def _sweep(self) -> None:
        """Expire parentless trees (remote `rpc-handle:*` subtrees, or
        abandoned spans): record the topmost span if it was slow."""
        now = time.monotonic()
        for tid in [
            t for t, ent in self.pending.items()
            if now - ent[0] > self.PENDING_TTL
        ]:
            self._expire(tid)

    def _expire(self, tid: bytes) -> None:
        """Finalize a pending trace that will never see a local root:
        the topmost local span (the one whose parent is remote or gone)
        stands in as the root."""
        ent = self.pending.pop(tid, None)
        if ent is None:
            return
        spans = ent[1]
        local_ids = {s.span_id for s in spans}
        tops = [s for s in spans if s.parent_id not in local_ids]
        if tops:
            root = max(tops, key=lambda s: s.end_ns - s.start_ns)
            self._maybe_record(root, spans)

    def snapshot(self) -> list[dict]:
        """Retained slow requests, slowest first."""
        return sorted(self.records, key=lambda r: -r["durationMs"])


def _extract_tree(root, spans) -> tuple[list, list]:
    """Split `spans` into (subtree under `root`, the rest).  Other local
    roots of the same trace keep buffering until they end or expire."""
    children: dict[bytes, list] = {}
    for s in spans:
        if s.parent_id is not None:
            children.setdefault(s.parent_id, []).append(s)
    tree, frontier = [root], [root.span_id]
    while frontier:
        kids = children.pop(frontier.pop(), [])
        tree.extend(kids)
        frontier.extend(k.span_id for k in kids)
    tree_ids = {id(s) for s in tree}
    return tree, [s for s in spans if id(s) not in tree_ids]


def _build_record(root, tree, duration_ms: float) -> dict:
    t0 = root.start_ns
    # phase waterfall (utils/latency.py): "why was THIS request
    # slow" answered per-phase, not just as a raw span tree
    try:
        from .latency import critical_path

        waterfall = critical_path(root, tree)
        if not waterfall["phases"]:
            waterfall = None
    # graft-lint: allow-swallow(waterfall is an optional enrichment of the slow record)
    except Exception:  # noqa: BLE001 — diagnostics must never raise
        waterfall = None
    return {
        "traceId": root.trace_id.hex(),
        "name": root.name,
        "start": root.start_ns / 1e9,
        "durationMs": round(duration_ms, 3),
        "ok": root.ok,
        "phases": waterfall,
        "attrs": {k: str(v) for k, v in root.attrs.items()},
        "spans": [
            {
                "name": s.name,
                "spanId": s.span_id.hex(),
                "parentSpanId": s.parent_id.hex()
                if s.parent_id
                else None,
                "startMs": round((s.start_ns - t0) / 1e6, 3),
                "durationMs": round((s.end_ns - s.start_ns) / 1e6, 3),
                "ok": s.ok,
                "attrs": {k: str(v) for k, v in s.attrs.items()},
            }
            for s in sorted(tree, key=lambda s: s.start_ns)
        ],
    }


class _SharedSpanFanout:
    """Process-wide span buffering shared by every ATTACHED recorder.

    Several in-process Garage nodes each run a flight recorder, but the
    tracer is process-global: registering every recorder as its own
    tracer hook made EVERY span buffer + finalize once per node — the
    single biggest event-loop cost under a concurrent S3 workload on an
    11-node in-process cluster (the span fan-out work scaled as
    nodes x spans, ~28% of total loop time in the EC PUT bench).  This
    is the SlowRequestRecorder analog of the PhaseAggregator singleton
    rule (utils/latency.py): buffer each span ONCE, extract each
    finished subtree ONCE, serialize a slow record ONCE, and hand the
    shared result to every attached recorder's ring.

    Recorders used directly as tracer hooks (tests, ad-hoc tooling)
    keep their standalone `on_span_end` path; `attach()`/`detach()` is
    how Garage wires them."""

    SWEEP_EVERY = SlowRequestRecorder.SWEEP_EVERY
    MAX_PENDING_TRACES = SlowRequestRecorder.MAX_PENDING_TRACES
    MAX_SPANS_PER_TRACE = SlowRequestRecorder.MAX_SPANS_PER_TRACE
    PENDING_TTL = SlowRequestRecorder.PENDING_TTL

    def __init__(self):
        self.recorders: list[SlowRequestRecorder] = []
        self.pending: dict[bytes, list] = {}
        self._calls = 0

    def attach(self, rec: SlowRequestRecorder) -> None:
        from .tracing import tracer

        if rec not in self.recorders:
            self.recorders.append(rec)
        if len(self.recorders) == 1:
            tracer.add_hook(self.on_span_end)

    def detach(self, rec: SlowRequestRecorder) -> None:
        from .tracing import tracer

        if rec in self.recorders:
            self.recorders.remove(rec)
        if not self.recorders:
            tracer.remove_hook(self.on_span_end)
            self.pending.clear()

    def on_span_end(self, span) -> None:
        self._calls += 1
        if self._calls % self.SWEEP_EVERY == 0:
            self._sweep()
        ent = self.pending.get(span.trace_id)
        if ent is None:
            if len(self.pending) >= self.MAX_PENDING_TRACES:
                self._expire(next(iter(self.pending)))
            ent = self.pending[span.trace_id] = [time.monotonic(), []]
        ent[0] = time.monotonic()
        if len(ent[1]) < self.MAX_SPANS_PER_TRACE:
            ent[1].append(span)
        else:
            for rec in self.recorders:
                rec.dropped += 1
        if span.parent_id is None:
            ent = self.pending.get(span.trace_id)
            if ent is None:
                return
            tree, rest = _extract_tree(span, ent[1])
            if rest:
                ent[1] = rest
            else:
                del self.pending[span.trace_id]
            self._record(span, tree)

    def _record(self, root, tree) -> None:
        duration_ms = (root.end_ns - root.start_ns) / 1e6
        record = None  # serialized at most once, shared by every ring
        for rec in self.recorders:
            if duration_ms < rec.threshold_ms:
                continue
            if record is None:
                record = _build_record(root, tree, duration_ms)
            rec.records.append(record)

    def _sweep(self) -> None:
        now = time.monotonic()
        for tid in [
            t for t, ent in self.pending.items()
            if now - ent[0] > self.PENDING_TTL
        ]:
            self._expire(tid)

    def _expire(self, tid: bytes) -> None:
        ent = self.pending.pop(tid, None)
        if ent is None:
            return
        spans = ent[1]
        local_ids = {s.span_id for s in spans}
        tops = [s for s in spans if s.parent_id not in local_ids]
        if tops:
            root = max(tops, key=lambda s: s.end_ns - s.start_ns)
            self._record(root, spans)


# the process-wide fanout (mirrors utils/latency.py `aggregator`)
span_fanout = _SharedSpanFanout()


def attach_recorder(rec: SlowRequestRecorder) -> None:
    """Register a recorder on the shared fanout (Garage.start)."""
    span_fanout.attach(rec)


def detach_recorder(rec: SlowRequestRecorder) -> None:
    span_fanout.detach(rec)


# severity ladder for flight events (rpc/transition.py ranks these for
# `--min-severity` filtering; unknown strings clamp to "info")
EVENT_SEVERITIES = ("info", "warn", "critical")


def record_event(name: str, attrs: dict, recorder=None,
                 severity: str = "info") -> None:
    """Append a synthetic EVENT record to the slow-request ring(s) and
    the dedicated event bank.

    Not a request: no span tree, zero duration, `ok: false` so the ring
    renderers surface it.  Used by planes that detect a state transition
    worth an operator's attention post-hoc — e.g. the durability
    observatory recording blocks entering `at_risk`/`unreadable`
    (block/durability.py), or the rebalance observatory's
    `transition-report` (rpc/transition.py).  `severity` is one of
    info/warn/critical and rides into `/v1/cluster/events` filtering.
    `recorder=None` fans out to every recorder attached to the shared
    span fanout (all in-process nodes); pass one explicitly for
    tests/ad-hoc tooling."""
    sev = severity if severity in EVENT_SEVERITIES else "info"
    rec = {
        "traceId": "",
        "name": name,
        "event": True,
        "severity": sev,
        "start": time.time(),
        "durationMs": 0.0,
        "ok": False,
        "phases": None,
        "attrs": {k: str(v) for k, v in attrs.items()},
        "spans": [],
    }
    registry.incr("flight_events_total", (("severity", sev),))
    targets = [recorder] if recorder is not None else list(span_fanout.recorders)
    for r in targets:
        r.records.append(rec)
        events = getattr(r, "events", None)
        if events is not None:
            events.append(rec)


def slow_response(recorder: "SlowRequestRecorder | None") -> dict:
    """The one serialization of the slow-request state, shared by the
    admin HTTP endpoint and the admin RPC op (so key casing cannot
    drift between the two transports)."""
    return {
        "enabled": recorder is not None,
        "thresholdMs": recorder.threshold_ms if recorder else None,
        "topK": recorder.top_k if recorder else None,
        "requests": recorder.snapshot() if recorder else [],
    }
