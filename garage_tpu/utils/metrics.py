"""Lightweight in-process metrics registry (reference: OpenTelemetry
meters exported via the admin Prometheus endpoint, src/util/metrics.rs +
doc/book/reference-manual/monitoring.md).

Three instrument kinds, rendered into Prometheus exposition text by the
admin API, no external deps:

  - counters                  incr(name, labels)
  - latency histograms        observe()/timer() — log2-spaced buckets from
                              0.25 ms to ~8 s plus +Inf, so p99 is visible
                              (BASELINE's S3 target is a p99), rendered in
                              standard `_bucket{le=…}`/`_count`/`_sum` form
                              (`_sum` in seconds)
  - value histograms          set_buckets(name, SIZE_BUCKETS) declares a
                              family whose observations are plain values
                              (batch sizes, byte counts), bucketed on its
                              own scheme; `_sum` is in the family's unit
  - gauges                    set_gauge() for pushed values, or
                              register_gauge(name, labels, fn) for values
                              polled at scrape time (queue lengths,
                              backlogs — reference src/block/metrics.rs,
                              src/table/metrics.rs pattern)
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager as _contextmanager

# 0.25 ms .. 8192 ms, log2-spaced (16 finite buckets)
BUCKETS = [0.00025 * (2 ** i) for i in range(16)]

# power-of-two count buckets (1 .. 65536): batch sizes, queue depths —
# matches the log2 batching the TPU dispatch layer actually does
SIZE_BUCKETS = [float(2 ** i) for i in range(17)]


class Metrics:
    def __init__(self) -> None:
        self.counters: dict[tuple, float] = defaultdict(float)
        # (name, labels) -> [count, sum, bucket_counts]
        self.durations: dict[tuple, list] = {}
        self.gauges: dict[tuple, float] = {}
        self._gauge_fns: dict[tuple, object] = {}
        # family name -> custom bucket bounds (absent = BUCKETS, seconds)
        self._family_buckets: dict[str, list[float]] = {}

    def incr(self, name: str, labels: tuple = (), by: float = 1) -> None:
        self.counters[(name, labels)] += by

    def set_buckets(self, name: str, buckets: list[float]) -> None:
        """Declare a value-histogram family with its own bucket bounds
        (e.g. SIZE_BUCKETS).  Idempotent; must precede the first observe
        — existing samples were bucketed under the old bounds, so a late
        re-declaration would silently corrupt the family."""
        if name in self._family_buckets:
            return
        if any(k[0] == name for k in self.durations):
            raise ValueError(
                f"set_buckets({name!r}) after the family has samples"
            )
        self._family_buckets[name] = buckets

    def observe(self, name: str, labels: tuple, value: float) -> None:
        bs = self._family_buckets.get(name, BUCKETS)
        d = self.durations.get((name, labels))
        if d is None:
            d = self.durations[(name, labels)] = [0, 0.0, [0] * (len(bs) + 1)]
        d[0] += 1
        d[1] += value
        for i, ub in enumerate(bs):
            if value <= ub:
                d[2][i] += 1
                return
        d[2][-1] += 1

    def timer(self, name: str, labels: tuple = (), lead: float = 0.0):
        """`lead` seconds are added to the observed duration — for time
        the caller already spent on the request before the timer could
        start (e.g. the admission queue wait ahead of request_metrics)."""
        return _Timer(self, name, labels, lead)

    def set_gauge(self, name: str, labels: tuple, value: float) -> None:
        self.gauges[(name, labels)] = value

    def register_gauge(self, name: str, labels: tuple, fn) -> None:
        """fn() is called at scrape time; exceptions drop the sample."""
        self._gauge_fns[(name, labels)] = fn

    def unregister_gauge(self, name: str, labels: tuple = ()) -> None:
        self._gauge_fns.pop((name, labels), None)
        self.gauges.pop((name, labels), None)

    # --- family aggregation (cluster telemetry digest, SLO tracker) ----------

    def counter_family_sum(self, name: str, pred=None) -> float:
        """Sum a counter family across every label set (optionally only
        those where `pred(labels_tuple)` holds) — e.g. total S3 requests
        regardless of method."""
        return sum(
            v
            for (n, labels), v in self.counters.items()
            if n == name and (pred is None or pred(labels))
        )

    def gauge_family_sum(self, name: str) -> float:
        """Sum a gauge family across label sets, calling registered
        scrape-time fns (a failing fn contributes 0, like render())."""
        total = sum(v for (n, _l), v in self.gauges.items() if n == name)
        for (n, _l), fn in list(self._gauge_fns.items()):
            if n != name:
                continue
            try:
                total += float(fn())
            # graft-lint: allow-swallow(a raising gauge fn means "no sample"; logging per scrape would spam)
            except Exception:  # noqa: BLE001
                continue
        return total

    def histogram_family_count(self, name: str, pred=None) -> int:
        """Total observations of a histogram family across label sets
        (optionally only those where `pred(labels_tuple)` holds) — e.g.
        how many canary probes errored, straight from the duration
        histogram's counts without a parallel counter family."""
        return sum(
            cnt
            for (n, labels), (cnt, _total, _buckets) in self.durations.items()
            if n == name and (pred is None or pred(labels))
        )

    def family_merge(self, name: str) -> tuple[int, float, list[int]] | None:
        """Merge a histogram family across all its label sets into one
        (count, sum, per-bucket counts) triple — the cluster digest wants
        ONE p99 for `api_s3_request_duration`, not one per method."""
        merged: list | None = None
        for (n, _labels), (cnt, total, buckets) in self.durations.items():
            if n != name:
                continue
            if merged is None:
                merged = [0, 0.0, [0] * len(buckets)]
            merged[0] += cnt
            merged[1] += total
            for i, c in enumerate(buckets):
                merged[2][i] += c
        return None if merged is None else (merged[0], merged[1], merged[2])

    def family_quantile(self, name: str, q: float) -> float | None:
        """Approximate quantile over the MERGED family histogram."""
        m = self.family_merge(name)
        if m is None or m[0] == 0:
            return None
        bs = self._family_buckets.get(name, BUCKETS)
        target = q * m[0]
        acc = 0
        for i, c in enumerate(m[2]):
            acc += c
            if acc >= target:
                return bs[i] if i < len(bs) else float("inf")
        return float("inf")

    def family_count_over(self, name: str, threshold: float) -> tuple[int, int]:
        """(total observations, observations ABOVE `threshold`) for a
        merged histogram family.  The threshold snaps to the NEAREST
        bucket bound: with log2 buckets a 1000 ms target evaluates at
        1024 ms — the alternative (largest bound <= threshold, 512 ms)
        would score all healthy 600-900 ms traffic as over-target and
        blow the latency SLO budget for a met SLO.  The latency-SLO
        tracker's "requests slower than the p99 target" feed."""
        m = self.family_merge(name)
        if m is None:
            return (0, 0)
        bs = self._family_buckets.get(name, BUCKETS)
        cutoff = min(bs, key=lambda b: abs(b - threshold))
        under = 0
        for i, c in enumerate(m[2][:-1]):
            if bs[i] <= cutoff:
                under += c
        return (m[0], m[0] - under)

    def quantile(self, name: str, labels: tuple, q: float) -> float | None:
        """Approximate quantile from the histogram (upper bucket bound)."""
        d = self.durations.get((name, labels))
        if d is None or d[0] == 0:
            return None
        bs = self._family_buckets.get(name, BUCKETS)
        target = q * d[0]
        acc = 0
        for i, c in enumerate(d[2]):
            acc += c
            if acc >= target:
                return bs[i] if i < len(bs) else float("inf")
        return float("inf")

    def render(self) -> list[str]:
        """Prometheus exposition lines.  Every family gets a `# TYPE`
        declaration before its first sample (the registry knows the
        instrument kind), so the output survives a strict format lint —
        asserted by the metrics-lint test against a live node."""
        lines = []
        last = None
        for (name, labels), v in sorted(self.counters.items()):
            if name != last:
                lines.append(f"# TYPE {name} counter")
                last = name
            lines.append(f"{name}{_fmt(labels)} {v:g}")
        last = None
        for (name, labels), (n, total, buckets) in sorted(self.durations.items()):
            if name != last:
                lines.append(f"# TYPE {name} histogram")
                last = name
            bs = self._family_buckets.get(name, BUCKETS)
            acc = 0
            for i, c in enumerate(buckets[:-1]):
                acc += c
                le = (("le", f"{bs[i]:g}"),)
                lines.append(f"{name}_bucket{_fmt(labels + le)} {acc}")
            lines.append(f'{name}_bucket{_fmt(labels + (("le", "+Inf"),))} {n}')
            lines.append(f"{name}_count{_fmt(labels)} {n}")
            # Prometheus-standard `_sum` for every histogram (latency
            # families used to render a nonstandard `_seconds_total`,
            # which histogram_quantile-adjacent recording rules and
            # `rate(x_sum)/rate(x_count)` averages can't use)
            if name in self._family_buckets:
                # value histogram: the sum is in the family's own unit
                lines.append(f"{name}_sum{_fmt(labels)} {total:g}")
            else:
                lines.append(f"{name}_sum{_fmt(labels)} {total:.6f}")
        gauges = dict(self.gauges)
        for (name, labels), fn in self._gauge_fns.items():
            try:
                gauges[(name, labels)] = float(fn())
            # graft-lint: allow-swallow(a raising gauge fn means "no sample"; logging per scrape would spam)
            except Exception:  # noqa: BLE001 — a dead gauge must not kill scrape
                continue
        last = None
        for (name, labels), v in sorted(gauges.items()):
            if name != last:
                lines.append(f"# TYPE {name} gauge")
                last = name
            lines.append(f"{name}{_fmt(labels)} {v:g}")
        return lines


def _esc(v) -> str:
    """Prometheus label-value escaping.  Label values can carry
    attacker-controlled strings (the admission plane's per-tenant
    gauges use the pre-auth CLAIMED key id / URL bucket name): an
    unescaped `"` or newline would corrupt the whole exposition and
    make the node metrics-dark to the scraper."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt(labels: tuple) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in labels) + "}"


class _Timer:
    def __init__(self, m: Metrics, name: str, labels: tuple, lead: float = 0.0):
        self.m, self.name, self.labels = m, name, labels
        self.lead = lead

    def __enter__(self):
        self.t0 = time.perf_counter() - self.lead
        return self

    def __exit__(self, exc_type, exc, tb):
        self.m.observe(self.name, self.labels, time.perf_counter() - self.t0)
        if exc_type is not None:
            self.m.incr(self.name + "_errors", self.labels)
        return False


# the process-wide registry (one storage daemon per process)
registry = Metrics()


@_contextmanager
def request_metrics(prefix: str, method: str, span_name: str,
                    lead_secs: float = 0.0, **span_attrs):
    """Shared HTTP-frontend instrumentation: `<prefix>_request_counter`,
    `<prefix>_request_duration` histogram, and a root tracing span that
    parents the request's table/block sub-spans.  Used by the s3, k2v
    and web servers so the pattern can't drift between them.
    `lead_secs` back-dates the duration sample by time already spent on
    the request before this wrapper ran (admission queue wait): the
    histogram must report the latency the client saw, or queue buildup
    is invisible to the latency-SLO burn signal."""
    from .tracing import span

    lbl = (("method", method),)
    registry.incr(f"{prefix}_request_counter", lbl)
    with span(span_name, method=method, **span_attrs):
        with registry.timer(f"{prefix}_request_duration", lbl, lead=lead_secs):
            yield
