"""Lightweight in-process metrics registry (reference: OpenTelemetry
meters exported via the admin Prometheus endpoint, src/util/metrics.rs +
doc/book/reference-manual/monitoring.md).

Counters and duration summaries keyed (name, labels); rendered into
Prometheus exposition text by the admin API.  No external deps, negligible
hot-path cost (a dict update per observation).
"""

from __future__ import annotations

import time
from collections import defaultdict


class Metrics:
    def __init__(self) -> None:
        self.counters: dict[tuple, float] = defaultdict(float)
        self.durations: dict[tuple, list] = defaultdict(lambda: [0, 0.0])

    def incr(self, name: str, labels: tuple = (), by: float = 1) -> None:
        self.counters[(name, labels)] += by

    def observe(self, name: str, labels: tuple, seconds: float) -> None:
        d = self.durations[(name, labels)]
        d[0] += 1
        d[1] += seconds

    def timer(self, name: str, labels: tuple = ()):
        return _Timer(self, name, labels)

    def render(self) -> list[str]:
        lines = []
        for (name, labels), v in sorted(self.counters.items()):
            lines.append(f"{name}{_fmt(labels)} {v:g}")
        for (name, labels), (n, total) in sorted(self.durations.items()):
            lines.append(f"{name}_count{_fmt(labels)} {n}")
            lines.append(f"{name}_seconds_total{_fmt(labels)} {total:.6f}")
        return lines


def _fmt(labels: tuple) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"


class _Timer:
    def __init__(self, m: Metrics, name: str, labels: tuple):
        self.m, self.name, self.labels = m, name, labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.m.observe(self.name, self.labels, time.perf_counter() - self.t0)
        if exc_type is not None:
            self.m.incr(self.name + "_errors", self.labels)
        return False


# the process-wide registry (one storage daemon per process)
registry = Metrics()
