"""Optional-dependency fallbacks (environment robustness).

The codebase prefers real `zstandard` (and `cryptography`, handled in
`net/crypto_compat.py`) when installed, but must keep working — daemon,
tests, chaos harness — in stripped containers that only carry the Python
standard library.  Rules:

- `zstandard` missing -> a zlib-backed shim with the same 3-symbol API
  (`compress`, `decompress`, `ZstdError`) is registered in `sys.modules`
  under the name "zstandard", so late `import zstandard` statements in
  tests and tools resolve to it too.  The shim produces ZLIB streams, not
  zstd frames: every node of a cluster must run the same implementation
  (a mixed real-zstd / shim cluster would fail to decompress each other's
  blocks — exactly like running different zstd-incompatible versions).
  Block files written by the shim are therefore only readable by shim
  nodes, and vice versa; both directions fail loudly with `ZstdError`
  because zlib and zstd reject each other's magic.

Import this module for its side effect before (or instead of) importing
`zstandard`; `garage_tpu/__init__` does so at package import.
"""

from __future__ import annotations

import sys
import types
import zlib


def _make_zstd_shim() -> types.ModuleType:
    mod = types.ModuleType("zstandard")
    mod.__doc__ = (
        "zlib-backed stand-in for the real `zstandard` package "
        "(garage_tpu.utils.depcompat); wire/disk streams are ZLIB, "
        "interoperable only with other shim nodes."
    )

    class ZstdError(Exception):
        pass

    def compress(data: bytes, level: int = 3) -> bytes:
        # zstd levels 1..22 ~ map into zlib 1..9; clamp rather than error
        return zlib.compress(data, min(max(int(level), 1), 9))

    def decompress(data: bytes, max_output_size: int = 0) -> bytes:
        try:
            return zlib.decompress(data)
        except zlib.error as e:
            raise ZstdError(f"decompression error: {e}") from e

    mod.ZstdError = ZstdError
    mod.compress = compress
    mod.decompress = decompress
    mod.COMPAT_SHIM = True  # marker for introspection/tests
    return mod


def ensure_zstandard() -> types.ModuleType:
    """Import real zstandard if present, else install + return the shim."""
    try:
        import zstandard  # noqa: F401

        return zstandard
    except ImportError:
        pass
    mod = sys.modules.get("zstandard")
    if mod is None:
        mod = _make_zstd_shim()
        sys.modules["zstandard"] = mod
    return mod


ensure_zstandard()
