"""Versioned serialization with in-place upgrade chains.

Mirrors reference src/util/migrate.rs:5-45: every persisted struct carries a
version-marker byte string prefix; decoding tries the current version first,
then walks back through the chain of previous versions, decoding with the
old schema and applying `migrate` hops forward.  This is what lets nodes of
different versions coexist and lets on-disk state upgrade in place.

A versioned class declares:

    class Thing(Migratable):
        VERSION_MARKER = b"G0thing"
        PREVIOUS: type | None = ThingV0   # or None for the initial format
        def to_obj(self) -> Any: ...
        @classmethod
        def from_obj(cls, obj) -> "Thing": ...
        @classmethod
        def migrate_from(cls, prev) -> "Thing": ...   # if PREVIOUS set

Encoded bytes are `VERSION_MARKER + msgpack(to_obj())`.
"""

from __future__ import annotations

from typing import Any, TypeVar

from .serde import pack, unpack  # noqa: F401 — canonical encoding, re-exported

M = TypeVar("M", bound="Migratable")


class Migratable:
    VERSION_MARKER: bytes = b""
    PREVIOUS: type | None = None

    def to_obj(self) -> Any:
        raise NotImplementedError

    @classmethod
    def from_obj(cls: type[M], obj: Any) -> M:
        raise NotImplementedError

    @classmethod
    def migrate_from(cls: type[M], prev: Any) -> M:
        raise NotImplementedError

    # --- encode/decode -----------------------------------------------------

    def encode(self) -> bytes:
        return self.VERSION_MARKER + pack(self.to_obj())

    @classmethod
    def decode(cls: type[M], data: bytes) -> M:
        if cls.VERSION_MARKER and data.startswith(cls.VERSION_MARKER):
            # A payload that fails to parse under the current schema falls
            # through to the previous version, like the reference
            # (src/util/migrate.rs:19-27 tries each version in turn).
            try:
                return cls.from_obj(unpack(data[len(cls.VERSION_MARKER):]))
            except Exception:
                if cls.PREVIOUS is None:
                    raise
        if not cls.VERSION_MARKER:
            # unversioned initial format
            return cls.from_obj(unpack(data))
        if cls.PREVIOUS is not None:
            prev = cls.PREVIOUS.decode(data)
            return cls.migrate_from(prev)
        raise ValueError(
            f"{cls.__name__}: unknown version marker in {data[:16]!r}"
        )
