"""Supervised asyncio task helpers (graft-lint orphan-task remedy).

The event loop holds only WEAK references to tasks: a fire-and-forget
``asyncio.create_task(...)`` can be garbage-collected mid-flight, and if
it fails the exception is dropped (surfacing — at best — as a "Task
exception was never retrieved" at interpreter shutdown, long after the
damage).  Every background spawn in the tree goes through
:func:`spawn_supervised` instead: the handle is anchored in a
process-wide registry until completion, and a failure is logged through
the correlated logger (``utils/log_fmt.py`` stamps trace_id/span_id on
the record, so a crashed ping task still points at its trace).

:func:`reap` is the shutdown-side counterpart: cancel-and-drain a batch
of tasks, consuming their results so abandoned exceptions are logged at
debug instead of leaking warnings.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Iterable

logger = logging.getLogger("garage.aio")

# strong references: the loop's own task set is a WeakSet
_supervised: set[asyncio.Task] = set()


def _on_done(task: asyncio.Task) -> None:
    _supervised.discard(task)
    if task.cancelled():
        return
    exc = task.exception()  # also marks the exception as retrieved
    if exc is not None:
        logger.error(
            "background task %r crashed: %r", task.get_name(), exc,
            exc_info=exc,
        )


def spawn_supervised(coro, name: str | None = None) -> asyncio.Task:
    """``create_task`` with a lifecycle: strong reference until the task
    completes, unregistered on completion, exception logged (with trace
    correlation) instead of dropped.  Cancellation is a normal outcome
    and logs nothing."""
    t = asyncio.create_task(coro, name=name)
    _supervised.add(t)
    t.add_done_callback(_on_done)
    return t


def supervised_count() -> int:
    """Live supervised tasks (tests assert the registry drains)."""
    return len(_supervised)


async def reap(
    tasks: Iterable[asyncio.Task | None],
    *,
    log: logging.Logger = logger,
    what: str = "task",
) -> None:
    """Cancel and drain `tasks`, consuming every outcome: cancellation
    is the expected result; a real exception from an abandoned task is
    diagnostic, not actionable — logged at debug, never raised.  Tasks
    that already finished get their exception retrieved too (no
    'exception was never retrieved' noise from e.g. a quorum wait that
    returned before a straggler failed).

    Drains via gather so (a) stragglers are awaited CONCURRENTLY —
    teardown costs the slowest cancel path, not the sum — and (b) a
    cancel aimed at the CALLER propagates: gather re-raises when the
    enclosing task is cancelled, while each child's own CancelledError
    is just a result row (a bare `except CancelledError` around
    per-task awaits would eat the caller's cancellation and let a
    cancelled long-poll handler keep running)."""
    tasks = [t for t in tasks if t is not None]
    for t in tasks:
        if not t.done():
            t.cancel()
    cur = asyncio.current_task()
    waits = [t for t in tasks if t is not cur]
    if not waits:
        return
    results = await asyncio.gather(*waits, return_exceptions=True)
    for t, r in zip(waits, results):
        if isinstance(r, asyncio.CancelledError):
            continue
        if isinstance(r, BaseException):
            log.debug("reaped %s %r: %r", what, t.get_name(), r)
