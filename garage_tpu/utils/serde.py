"""Canonical msgpack encoding — THE wire/persistence serialization.

One definition so the RPC layer, CRDTs and persisted state can never fork
their encoding options.
"""

from __future__ import annotations

from typing import Any

import msgpack


def pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False, use_list=True)
