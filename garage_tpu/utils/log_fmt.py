"""Structured log formatting with trace correlation.

Every record emitted while a tracing span is active is stamped with the
span's `trace_id`/`span_id` (hex), so bench and chaos logs can be joined
against exported traces — grep a trace id from the OTLP sink and the
matching daemon log lines fall out.  With no active span (or tracing
off) the fields are empty strings, never missing: format strings and
JSON consumers see a stable schema.

Two output shapes, selected by config (`log_format = "text" | "json"`,
env override GARAGE_LOG_FORMAT):

  text   classic single-line, with a `[trace_id]` suffix only when one
         is present (quiet logs stay quiet)
  json   JSON lines — one object per record (ts, level, logger, msg,
         trace_id, span_id, + exc when present), the shape log
         pipelines ingest without a parse grammar

`setup_logging()` is the one entry point (cli/main.py calls it at
process start and re-applies it once the config is read).
"""

from __future__ import annotations

import json
import logging
import time


class TraceContextFilter(logging.Filter):
    """Stamps `record.trace_id` / `record.span_id` from the current
    tracing span.  A Filter (not a Formatter) so every handler — text,
    JSON, a test's capture handler — sees the fields."""

    def filter(self, record: logging.LogRecord) -> bool:
        from .tracing import tracer

        s = tracer.current()
        if s is not None:
            record.trace_id = s.trace_id.hex()
            record.span_id = s.span_id.hex()
        else:
            record.trace_id = ""
            record.span_id = ""
        return True


class TextFormatter(logging.Formatter):
    """Classic text line + ` [trace=<id> span=<id>]` suffix when traced."""

    def __init__(self):
        super().__init__("%(asctime)s %(name)s %(levelname)s %(message)s")

    def format(self, record: logging.LogRecord) -> str:
        line = super().format(record)
        tid = getattr(record, "trace_id", "")
        if tid:
            line += f" [trace={tid} span={getattr(record, 'span_id', '')}]"
        return line


class JsonLinesFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(
                record.created if record.created else time.time(), 6
            ),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "trace_id": getattr(record, "trace_id", ""),
            "span_id": getattr(record, "span_id", ""),
        }
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=repr)


def setup_logging(fmt: str = "text", level: str | int = "INFO") -> None:
    """(Re)configure the root logger: one stderr handler with the chosen
    formatter and the trace-context filter.  Idempotent — safe to call
    again after the config file is read."""
    root = logging.getLogger()
    root.setLevel(level)
    # replace only handlers we installed (marked), preserving pytest's
    # capture handlers and anything the embedding app configured
    for h in list(root.handlers):
        if getattr(h, "_garage_log_fmt", False):
            root.removeHandler(h)
    handler = logging.StreamHandler()
    handler._garage_log_fmt = True  # type: ignore[attr-defined]
    handler.setFormatter(
        JsonLinesFormatter() if fmt == "json" else TextFormatter()
    )
    handler.addFilter(TraceContextFilter())
    root.addHandler(handler)
