"""Latency X-ray: phase-level critical-path attribution for the S3 data
plane.

ROADMAP item 1 (the EC write-latency gap: EC(8,3) PUT p99 is 3.16x the
3-replica baseline) needs to know *where* those milliseconds go before
the PUT pipeline is rebuilt as an overlapped one.  The tracer (PR 2)
records spans and the flight recorder (PR 3) retains slow traces, but
nothing decomposed a request into phases or measured how sequential the
pipeline actually is.  This module closes that gap:

  - a fixed **phase catalogue** (`PHASES`): every stage of the block
    write/read pipeline is wrapped in a `phase:<name>` span carrying a
    `phase` attribute from this catalogue — auth, chunk, encode, hash,
    fan-out, quorum wait, metadata commit on the PUT side; index read,
    piece fetch, decode, stream-out on the GET side.  The catalogue is
    closed on purpose: `{op,phase}` label cardinality is bounded and the
    metrics-lint tier-1 test fails on any combination outside it.

  - `critical_path()` walks a finished span tree and computes per-phase
    **exclusive** wall time: same-phase spans that overlap (the parallel
    piece fan-out) merge into one wall-clock interval — parallelism must
    not double-count — and a phase span's interval excludes descendant
    spans carrying a *different* phase.  `quorum_wait` additionally
    excludes the trace-global `fanout` union (the quorum wait *is* the
    send window; its exclusive time is the tail where every send is done
    but a quorum still isn't).  From those intervals it derives:

      coverage            union of all phase intervals / request wall —
                          how much of the request the catalogue explains
      overlap efficiency  wall / sum of phase times — 1.0 means the
                          phases ran back-to-back (fully sequential, the
                          thing ROADMAP item 1 will fix); below 1.0 the
                          pipeline genuinely overlaps
      critical-path share per-phase fraction of the attributed time

  - `PhaseAggregator`, a tracer span-end hook (PR 3 pattern: attaching
    it enables span creation with no OTLP sink), feeds per-request phase
    times into `api_s3_phase_duration{op,phase}` histograms plus an
    `api_s3_overlap_efficiency{op}` EWMA gauge, and keeps a rolling
    window per op so `GET /v1/debug/latency` / `cli debug latency` can
    serve a live phase waterfall (p50/p95/p99 per phase, share, overlap
    efficiency) with zero external collectors.

The aggregator is a process-wide singleton (like the metrics registry it
feeds): several in-process test nodes share one tracer and one registry,
so per-node aggregators would multiply every observation by the node
count.  `enable()`/`disable()` refcount the tracer hook.
"""

from __future__ import annotations

import collections
import logging
import time

from .metrics import registry as _registry
from .tracing import NOOP_SPAN, tracer

logger = logging.getLogger("garage.latency")

# The CLOSED phase catalogue.  Adding a stage here is a reviewed schema
# change: doc/monitoring.md documents each phase and the metrics-lint
# test enforces that `api_s3_phase_duration` never exposes a label
# outside this tuple.
PHASES = (
    "auth",         # SigV4 verification + access-key fetch
    "chunk",        # reading/chunking the request body
    "codec_batch_wait",  # queue time in the codec batcher before dispatch
    "encode",       # EC piece encoding (or replica compression)
    "hash",         # content hashing (md5/sha/blake2) + SSE transform
    "fanout",       # piece/replica sends to the write set
    "quorum_wait",  # waiting for quorum beyond the send window
    "meta_commit",  # object/version/block-ref table commits
    "meta_coalesce_wait",  # queue time in the table insert coalescer
    "index_read",   # object/version/bucket metadata reads
    "piece_fetch",  # gathering block bytes / EC pieces
    "decode",       # EC decode + post-decode verification
    "stream_out",   # writing response bytes to the client
)
_PHASE_SET = frozenset(PHASES)

# Operation classes a request root may be stamped with (`mark_op`).
OPS = ("put", "get", "head", "delete", "upload_part")
_OP_SET = frozenset(OPS)

# Phases whose exclusive time excludes another phase's trace-global
# interval union even without a tree ancestry link: the EC quorum wait
# runs CONCURRENTLY with the sends it waits on (sibling spans, different
# tasks), and counting that window twice would fake pipeline overlap.
RESIDUAL_OF = {"quorum_wait": ("fanout",)}

ROOT_SPAN_NAME = "api:s3"


def phase_span(name: str):
    """A `phase:<name>` span from the fixed catalogue — the ONLY way
    instrumentation sites attach a phase attribute, so an ad-hoc name
    can't leak into the label space.  No-op when tracing is off."""
    if not tracer.enabled:
        return NOOP_SPAN
    assert name in _PHASE_SET, f"phase {name!r} not in the catalogue"
    return tracer.span("phase:" + name, phase=name)


def mark_op(op: str) -> None:
    """Stamp the operation class on the innermost open span — handlers
    call this at their top, where that span is the `api:s3` request
    root.  Unknown ops are dropped (bounded label space)."""
    if op not in _OP_SET:
        return
    s = tracer.current()
    if s is not None:
        s.attrs["op"] = op


# --- interval helpers ---------------------------------------------------------


def _merge(ivs: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Overlapping/adjacent intervals -> disjoint sorted intervals."""
    if not ivs:
        return []
    ivs = sorted(ivs)
    out = [ivs[0]]
    for s, e in ivs[1:]:
        ls, le = out[-1]
        if s <= le:
            if e > le:
                out[-1] = (ls, e)
        else:
            out.append((s, e))
    return out


def _subtract(
    iv: tuple[int, int], cuts: list[tuple[int, int]]
) -> list[tuple[int, int]]:
    """Pieces of `iv` not covered by `cuts` (cuts disjoint + sorted)."""
    s, e = iv
    out = []
    for cs, ce in cuts:
        if ce <= s or cs >= e:
            continue
        if cs > s:
            out.append((s, cs))
        s = max(s, ce)
        if s >= e:
            break
    if s < e:
        out.append((s, e))
    return out


def _span_len(ivs: list[tuple[int, int]]) -> int:
    return sum(e - s for s, e in ivs)


# --- critical-path analysis ---------------------------------------------------


def critical_path(root, spans) -> dict:
    """Per-phase exclusive-time attribution over one finished span tree.

    `root`/`spans` are Span-like objects (`span_id`, `parent_id`,
    `start_ns`, `end_ns`, `attrs`); `spans` is every span of the trace
    (the root itself may or may not be included).  Returns::

        {"wallMs", "attributedMs", "sumMs", "coverage",
         "overlapEfficiency", "phases": {phase: {"ms", "share"}}}

    Semantics (asserted by tests/test_latency_xray.py):
      - same-phase spans merge on the wall clock first — N parallel
        fan-out RPCs taking 50 ms each over a 60 ms window contribute
        60 ms, not N*50;
      - a phase span excludes descendant spans carrying a different
        phase (nested stages are not counted twice);
      - `RESIDUAL_OF` phases additionally exclude their counterpart
        phases' trace-global union (see module docstring);
      - everything is clipped to the root's [start, end] window —
        background stragglers ending after the response don't inflate
        the request's attribution.
    """
    wall_ns = max(root.end_ns - root.start_ns, 1)
    children: dict[bytes, list] = {}
    for s in spans:
        if s.parent_id is not None:
            children.setdefault(s.parent_id, []).append(s)

    # raw per-phase interval unions (for RESIDUAL_OF and coverage)
    raw: dict[str, list[tuple[int, int]]] = {}
    phase_spans = []
    for s in spans:
        ph = s.attrs.get("phase")
        if ph not in _PHASE_SET:
            continue
        lo = max(s.start_ns, root.start_ns)
        hi = min(s.end_ns, root.end_ns)
        if hi <= lo:
            continue
        phase_spans.append((s, ph, (lo, hi)))
        raw.setdefault(ph, []).append((lo, hi))
    raw = {ph: _merge(ivs) for ph, ivs in raw.items()}

    exclusive: dict[str, list[tuple[int, int]]] = {}
    for s, ph, iv in phase_spans:
        # descendant spans with a DIFFERENT phase cut this span's interval
        cuts: list[tuple[int, int]] = []
        stack = [s.span_id]
        while stack:
            for c in children.get(stack.pop(), []):
                cph = c.attrs.get("phase")
                if cph in _PHASE_SET and cph != ph:
                    cuts.append((c.start_ns, c.end_ns))
                else:
                    stack.append(c.span_id)
        for other in RESIDUAL_OF.get(ph, ()):
            cuts.extend(raw.get(other, ()))
        pieces = _subtract(iv, _merge(cuts)) if cuts else [iv]
        exclusive.setdefault(ph, []).extend(pieces)

    phases_ns = {ph: _span_len(_merge(ivs)) for ph, ivs in exclusive.items()}
    phases_ns = {ph: ns for ph, ns in phases_ns.items() if ns > 0}
    total_ns = sum(phases_ns.values())
    covered_ns = _span_len(
        _merge([iv for ivs in exclusive.values() for iv in ivs])
    )
    return {
        "wallMs": round(wall_ns / 1e6, 3),
        "attributedMs": round(covered_ns / 1e6, 3),
        "sumMs": round(total_ns / 1e6, 3),
        "coverage": round(covered_ns / wall_ns, 4),
        "overlapEfficiency": (
            round(wall_ns / total_ns, 4) if total_ns else None
        ),
        # coverage-independent companion: attributed-union / sum.  1.0 =
        # the attributed phases are disjoint (sequential); below 1.0 they
        # genuinely overlap.  overlapEfficiency (wall / sum, the ISSUE
        # metric) mixes in uncovered wall time — with coverage < 1 it can
        # read ~1.0 for a pipeline that does overlap; this one can't.
        "sequentiality": (
            round(covered_ns / total_ns, 4) if total_ns else None
        ),
        "phases": {
            ph: {
                "ms": round(ns / 1e6, 3),
                "share": round(ns / total_ns, 4),
            }
            for ph, ns in sorted(phases_ns.items(), key=lambda kv: -kv[1])
        },
    }


# --- rolling aggregation (the tracer hook) ------------------------------------


class PhaseAggregator:
    """Buffers spans per trace (SlowRequestRecorder pattern) and, when an
    `api:s3` root stamped with a catalogue op ends, runs critical_path()
    over its tree: histograms + EWMA gauge into the registry, the full
    result into a bounded per-op window for the waterfall endpoint."""

    SWEEP_EVERY = 512
    MAX_PENDING_TRACES = 1024
    # generous: a multi-hundred-MiB streamed GET emits several spans per
    # block (fetch/decode/stream_out + rpc layers).  A trace that still
    # overflows is marked truncated and NOT recorded — an absent sample
    # is honest, a waterfall missing its tail phases is corrupt.
    MAX_SPANS_PER_TRACE = 4096
    PENDING_TTL = 30.0
    WINDOW = 256  # retained analyses per op
    EWMA_ALPHA = 0.2

    def __init__(self, registry=None):
        self.registry = registry if registry is not None else _registry
        # trace id -> [last_touch_monotonic, [spans]]
        self.pending: dict[bytes, list] = {}
        self.recent: dict[str, collections.deque] = {}
        self.recorded = 0
        self._overlap_ewma: dict[str, float] = {}
        self._calls = 0

    def reset(self) -> None:
        """Drop buffered traces + the rolling window (test isolation —
        the singleton outlives any one in-process node)."""
        self.pending.clear()
        self.recent.clear()
        self._overlap_ewma.clear()
        self.recorded = 0

    # the tracer hook — called on the event loop for every finished span
    def on_span_end(self, span) -> None:
        self._calls += 1
        if self._calls % self.SWEEP_EVERY == 0:
            self._sweep()
        ent = self.pending.get(span.trace_id)
        if ent is None:
            if span.parent_id is None:
                # single-span trace (background table op, noise root):
                # nothing buffered, nothing to analyze
                self._maybe_record(span, [span])
                return
            if len(self.pending) >= self.MAX_PENDING_TRACES:
                self.pending.pop(next(iter(self.pending)), None)
            # [last_touch, spans, truncated]
            ent = self.pending[span.trace_id] = [time.monotonic(), [], False]
        ent[0] = time.monotonic()
        if len(ent[1]) < self.MAX_SPANS_PER_TRACE:
            ent[1].append(span)
        else:
            ent[2] = True
        if span.parent_id is None:
            ent = self.pending.pop(span.trace_id)
            if not ent[2]:
                self._maybe_record(span, ent[1])

    def _maybe_record(self, root, spans) -> None:
        if root.name != ROOT_SPAN_NAME:
            return
        op = root.attrs.get("op")
        if op not in _OP_SET:
            return
        try:
            result = critical_path(root, spans)
        except Exception as e:  # noqa: BLE001 — hooks must not fail spans
            logger.debug("critical_path failed: %r", e)
            return
        if not result["phases"]:
            return
        self._record(op, result)

    def _record(self, op: str, result: dict) -> None:
        r = self.registry
        for ph, st in result["phases"].items():
            if ph not in _PHASE_SET:  # defensive: bounded label space
                continue
            r.observe(
                "api_s3_phase_duration",
                (("op", op), ("phase", ph)),
                st["ms"] / 1000.0,
            )
        eff = result["overlapEfficiency"]
        if eff is not None:
            prev = self._overlap_ewma.get(op)
            ewma = (
                eff if prev is None
                else self.EWMA_ALPHA * eff + (1 - self.EWMA_ALPHA) * prev
            )
            self._overlap_ewma[op] = ewma
            r.set_gauge(
                "api_s3_overlap_efficiency", (("op", op),), round(ewma, 4)
            )
        dq = self.recent.get(op)
        if dq is None:
            dq = self.recent[op] = collections.deque(maxlen=self.WINDOW)
        dq.append(result)
        self.recorded += 1

    def _sweep(self) -> None:
        now = time.monotonic()
        for tid in [
            t for t, ent in self.pending.items()
            if now - ent[0] > self.PENDING_TTL
        ]:
            self.pending.pop(tid, None)

    # --- waterfall snapshot ---------------------------------------------------

    @staticmethod
    def _mean_of(records: list[dict], key: str) -> float:
        vals = [r[key] for r in records if r.get(key) is not None]
        return round(sum(vals) / len(vals), 4) if vals else 0.0

    @staticmethod
    def _pcts(vals: list[float]) -> dict[str, float]:
        vals = sorted(vals)

        def p(q: float) -> float:
            return vals[min(len(vals) - 1, int(q * len(vals)))]

        return {
            "p50": round(p(0.50), 3),
            "p95": round(p(0.95), 3),
            "p99": round(p(0.99), 3),
        }

    def snapshot(self) -> dict:
        """Rolling waterfall per op: wall/phase percentiles, aggregate
        critical-path share, coverage, overlap efficiency."""
        out: dict[str, dict] = {}
        for op, dq in self.recent.items():
            records = list(dq)
            if not records:
                continue
            per_phase: dict[str, list[float]] = {}
            for rec in records:
                for ph, st in rec["phases"].items():
                    per_phase.setdefault(ph, []).append(st["ms"])
            sum_all = sum(ms for v in per_phase.values() for ms in v)
            out[op] = {
                "count": len(records),
                "wallMs": self._pcts([r["wallMs"] for r in records]),
                "coverage": round(
                    sum(r["coverage"] for r in records) / len(records), 4
                ),
                "overlapEfficiency": self._mean_of(
                    records, "overlapEfficiency"
                ),
                "sequentiality": self._mean_of(records, "sequentiality"),
                "phases": {
                    ph: {
                        **self._pcts(vals),
                        "criticalPathShare": round(
                            sum(vals) / sum_all, 4
                        ) if sum_all else 0.0,
                    }
                    for ph, vals in sorted(
                        per_phase.items(), key=lambda kv: -sum(kv[1])
                    )
                },
            }
        return out


# process-wide aggregator: the registry it feeds is process-global, and
# several in-process nodes share one tracer — per-node instances would
# multiply every histogram observation by the node count
aggregator = PhaseAggregator()

_refs = 0


def enable() -> None:
    """Attach the aggregator hook (refcounted — every in-process Garage
    with `[admin] latency_xray` calls this at start)."""
    global _refs
    _refs += 1
    tracer.add_hook(aggregator.on_span_end)


def disable() -> None:
    global _refs
    _refs = max(0, _refs - 1)
    if _refs == 0:
        tracer.remove_hook(aggregator.on_span_end)


def latency_response() -> dict:
    """The one serialization of the latency-X-ray state, shared by the
    admin HTTP endpoint and the admin RPC op (PR 3's slow_response
    pattern: key casing cannot drift between transports)."""
    return {
        "enabled": _refs > 0,
        "phases": list(PHASES),
        "ops": aggregator.snapshot(),
    }
