"""Bounded-memory streaming sketches for traffic analytics.

The traffic observatory (rpc/traffic.py) must answer "which objects are
hot, how skewed is the keyspace" over millions of distinct keys without
storing millions of counters.  Two classic mergeable summaries:

  - `SpaceSaving` — top-K heavy hitters (Metwally, Agrawal & El Abbadi,
    "Efficient Computation of Frequent and Top-k Elements in Data
    Streams", ICDT 2005).  At most `capacity` tracked keys; for every
    key the stored count is an UPPER bound on its true (decayed) weight
    and `count - error` a lower bound; any key whose true weight exceeds
    total/capacity is guaranteed tracked.

  - `CountMin` — per-key frequency estimates over the whole keyspace
    (Cormode & Muthukrishnan, "An Improved Data Stream Summary: The
    Count-Min Sketch and its Applications", J. Algorithms 2005).
    `depth x width` counters; estimates are upper bounds with error
    <= e * total / width at probability 1 - e^-depth.

Both support:

  - exponential time-decay (`halflife` seconds): old traffic fades so
    "hot" means hot NOW, not hot since process start.  Decay is applied
    in lazy O(state) sweeps (at most ~16 per halflife), never per
    update — the S3 request path pays dict arithmetic only.
  - `merge()` for federation: combining two sketches keeps the
    upper/lower-bound guarantees (mergeable-summaries style); merging
    is exact (pointwise) whenever the union fits the capacity, so the
    associativity property tests can pin it without error slack.

Hashing is keyed BLAKE2b, deterministic across processes (Python's
builtin `hash` is salted per process and would break cross-node
merges).  Stdlib only — this rides the analyzer-grade import budget.
"""

from __future__ import annotations

import heapq
import math
import time
from hashlib import blake2b

__all__ = ["SpaceSaving", "CountMin", "zipf_exponent"]

# lazy-decay sweep granularity: state is rescaled at most this many
# times per halflife (each sweep is O(capacity) / O(width*depth))
_SWEEPS_PER_HALFLIFE = 16


class _Decayed:
    """Shared lazy exponential-decay bookkeeping."""

    def __init__(self, halflife: float | None, clock):
        if halflife is not None and halflife <= 0:
            raise ValueError("halflife must be positive (or None)")
        self.halflife = halflife
        self.clock = clock
        self._last_decay = clock()

    def _decay_factor(self) -> float | None:
        """Factor to rescale all state by, or None when it's not time
        yet.  Advances the decay anchor when a factor is returned."""
        if self.halflife is None:
            return None
        now = self.clock()
        dt = now - self._last_decay
        if dt < self.halflife / _SWEEPS_PER_HALFLIFE:
            return None
        self._last_decay = now
        return 0.5 ** (dt / self.halflife)


class SpaceSaving(_Decayed):
    """Space-Saving top-K summary with optional exponential decay.

    `counts[k]` is an upper bound on k's decayed weight; `errors[k]`
    bounds the overestimate (so `counts[k] - errors[k]` is a lower
    bound).  `len(counts) <= capacity` ALWAYS — the memory bound is
    structural, not amortized.
    """

    def __init__(
        self,
        capacity: int,
        halflife: float | None = None,
        clock=time.monotonic,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        super().__init__(halflife, clock)
        self.capacity = int(capacity)
        self.counts: dict[str, float] = {}
        self.errors: dict[str, float] = {}
        self.total = 0.0  # decayed total stream weight
        # lazy min-heap of (count, key): entries go stale when a key's
        # count grows; eviction pops/corrects until the top is accurate
        self._heap: list[tuple[float, str]] = []

    # --- decay ---------------------------------------------------------------

    def _maybe_decay(self) -> None:
        f = self._decay_factor()
        if f is None:
            return
        for k in self.counts:
            self.counts[k] *= f
            self.errors[k] *= f
        self.total *= f
        self._rebuild_heap()

    def _rebuild_heap(self) -> None:
        self._heap = [(c, k) for k, c in self.counts.items()]
        heapq.heapify(self._heap)

    # --- updates -------------------------------------------------------------

    def incr(self, key: str, by: float = 1.0) -> None:
        self._maybe_decay()
        self.total += by
        cur = self.counts.get(key)
        if cur is not None:
            self.counts[key] = cur + by
            heapq.heappush(self._heap, (cur + by, key))
        elif len(self.counts) < self.capacity:
            self.counts[key] = by
            self.errors[key] = 0.0
            heapq.heappush(self._heap, (by, key))
        else:
            # evict the true minimum; the newcomer inherits its count as
            # the classic Space-Saving overestimate
            min_count, min_key = self._accurate_min()
            del self.counts[min_key]
            del self.errors[min_key]
            heapq.heappop(self._heap)
            self.counts[key] = min_count + by
            self.errors[key] = min_count
            heapq.heappush(self._heap, (min_count + by, key))
        # stale-entry bound: hot keys push a heap entry per increment
        if len(self._heap) > 4 * self.capacity + 64:
            self._rebuild_heap()

    def _accurate_min(self) -> tuple[float, str]:
        """Top of the lazy heap with stale entries corrected in place."""
        while True:
            c, k = self._heap[0]
            cur = self.counts.get(k)
            if cur is None:
                heapq.heappop(self._heap)  # evicted earlier
                continue
            if cur != c:
                heapq.heapreplace(self._heap, (cur, k))
                continue
            return c, k

    # --- queries -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.counts)

    def min_count(self) -> float:
        """Upper bound on any UNTRACKED key's weight (0 below capacity)."""
        self._maybe_decay()
        if len(self.counts) < self.capacity or not self.counts:
            return 0.0
        return self._accurate_min()[0]

    def estimate(self, key: str) -> float:
        """Upper-bound weight estimate for `key`.  Applies the lazy
        decay first — a read-only consumer after a quiet period must
        see the same decayed scale top() reports."""
        self._maybe_decay()
        c = self.counts.get(key)
        return c if c is not None else self.min_count()

    def top(self, n: int | None = None) -> list[tuple[str, float, float]]:
        """[(key, count, error)] sorted by count desc (key asc ties —
        deterministic output keeps merges/tests reproducible)."""
        self._maybe_decay()
        items = sorted(
            ((k, c, self.errors[k]) for k, c in self.counts.items()),
            key=lambda t: (-t[1], t[0]),
        )
        return items if n is None else items[:n]

    # --- federation ----------------------------------------------------------

    def merge(self, other: "SpaceSaving") -> "SpaceSaving":
        """Combined summary (self unchanged).  Pointwise-exact when the
        key union fits `capacity`; beyond that, keeps the heaviest
        `capacity` keys with composed error bounds (a key untracked by
        one side contributes that side's min_count as both count and
        error — the mergeable-summaries upper-bound recipe)."""
        if (self.capacity, self.halflife) != (
            other.capacity, other.halflife,
        ):
            # a smaller-capacity side computes min_count against its own
            # capacity, which breaks the untracked-key bound for the
            # merged result — mirror CountMin's geometry check
            raise ValueError(
                "SpaceSaving merge requires identical capacity/halflife"
            )
        out = SpaceSaving(self.capacity, self.halflife, self.clock)
        m1, m2 = self.min_count(), other.min_count()
        union = set(self.counts) | set(other.counts)
        merged = []
        for k in union:
            c = self.counts.get(k, m1) + other.counts.get(k, m2)
            e = self.errors.get(k, m1) + other.errors.get(k, m2)
            merged.append((k, c, e))
        merged.sort(key=lambda t: (-t[1], t[0]))
        for k, c, e in merged[: self.capacity]:
            out.counts[k] = c
            out.errors[k] = e
        out.total = self.total + other.total
        out._rebuild_heap()
        return out


class CountMin(_Decayed):
    """Count-Min sketch with optional exponential decay.

    Estimates are upper bounds on the (decayed) weight; width/depth/seed
    must match for `merge()` (the hash family defines the cell layout).
    """

    def __init__(
        self,
        width: int = 1024,
        depth: int = 4,
        halflife: float | None = None,
        clock=time.monotonic,
        seed: bytes = b"garage-tpu-traffic",
    ):
        if width < 8 or depth < 1 or depth > 16:
            raise ValueError("want width >= 8 and 1 <= depth <= 16")
        super().__init__(halflife, clock)
        self.width = int(width)
        self.depth = int(depth)
        self.seed = seed
        self.rows: list[list[float]] = [
            [0.0] * self.width for _ in range(self.depth)
        ]
        self.total = 0.0

    def _indexes(self, key: str | bytes) -> list[int]:
        if isinstance(key, str):
            key = key.encode("utf-8", "surrogateescape")
        d = blake2b(key, digest_size=4 * self.depth, key=self.seed).digest()
        return [
            int.from_bytes(d[4 * i : 4 * i + 4], "big") % self.width
            for i in range(self.depth)
        ]

    def _maybe_decay(self) -> None:
        f = self._decay_factor()
        if f is None:
            return
        for row in self.rows:
            for i, v in enumerate(row):
                if v:
                    row[i] = v * f
        self.total *= f

    def incr(self, key: str | bytes, by: float = 1.0) -> None:
        self._maybe_decay()
        self.total += by
        for row, i in zip(self.rows, self._indexes(key)):
            row[i] += by

    def estimate(self, key: str | bytes) -> float:
        self._maybe_decay()
        return min(
            row[i] for row, i in zip(self.rows, self._indexes(key))
        )

    def merge(self, other: "CountMin") -> "CountMin":
        if (self.width, self.depth, self.seed) != (
            other.width, other.depth, other.seed,
        ):
            raise ValueError("CountMin merge requires identical geometry")
        out = CountMin(
            self.width, self.depth, self.halflife, self.clock, self.seed
        )
        for or_, r1, r2 in zip(out.rows, self.rows, other.rows):
            for i in range(self.width):
                v = r1[i] + r2[i]
                if v:
                    or_[i] = v
        out.total = self.total + other.total
        return out


def zipf_exponent(counts: list[float]) -> float | None:
    """Least-squares zipf skew estimate from rank-ordered counts: the
    slope of ln(count) on ln(rank).  `s ~ 0` is uniform traffic, `s >= 1`
    the classic heavy-skew regime.  None below 3 positive points (two
    points always fit exactly — that is measurement, not estimation)."""
    pts = [
        (math.log(rank), math.log(c))
        for rank, c in enumerate(
            (c for c in counts if c > 0), start=1
        )
    ]
    if len(pts) < 3:
        return None
    n = len(pts)
    mx = sum(x for x, _ in pts) / n
    my = sum(y for _, y in pts) / n
    var = sum((x - mx) ** 2 for x, _ in pts)
    if var <= 0:
        return None
    cov = sum((x - mx) * (y - my) for x, y in pts)
    return round(max(0.0, -cov / var), 4)
