"""Daemon configuration: a single TOML file plus env-var / file secrets.

Mirrors reference src/util/config.rs:13-142 (knob inventory, defaults) and
src/garage/secrets.rs (secret layering: inline < file < env).  New in the
rebuild: `replication_mode` accepts `"ec:k:m"` to enable the TPU-batched
erasure-coded block codec (BASELINE.json north star).
"""

from __future__ import annotations

import os
import re

try:  # py3.11+ stdlib; absent on 3.10 containers — only read_config needs it
    import tomllib
except ImportError:
    tomllib = None
from dataclasses import dataclass, field
from typing import Any

DEFAULT_BLOCK_SIZE = 1024 * 1024  # 1 MiB, config.rs:273-275
DEFAULT_COMPRESSION_LEVEL = 1  # zstd level, config.rs:284


@dataclass
class DataDir:
    path: str
    capacity: int | None = None  # bytes; None = unlimited single-dir mode
    read_only: bool = False


@dataclass
class S3ApiConfig:
    api_bind_addr: str | None = None
    s3_region: str = "garage"
    root_domain: str | None = None


@dataclass
class K2VApiConfig:
    api_bind_addr: str | None = None


@dataclass
class WebConfig:
    bind_addr: str | None = None
    root_domain: str = ".web.garage"
    add_host_to_metrics: bool = False


@dataclass
class AdminConfig:
    api_bind_addr: str | None = None
    admin_token: str | None = None
    admin_token_file: str | None = None
    metrics_token: str | None = None
    metrics_token_file: str | None = None
    trace_sink: str | None = None
    # flight recorder (utils/flight.py): slow-request ring buffer served
    # from /v1/debug/slow — on by default so a node self-diagnoses with
    # zero external collectors (enables span creation without a sink)
    flight_recorder: bool = True
    slow_request_threshold_msec: float = 500.0
    slow_request_top_k: int = 64
    # event-loop watchdog: scheduling-lag histogram + blocked-loop task
    # dumps; 0 disables
    event_loop_watchdog_threshold_msec: float = 250.0
    # stall auto-capture (utils/profiler.StallProfiler): when the
    # watchdog counts a stall, sample the wedged process for a burst and
    # attach the top stacks to a `loop-stall-profile` flight event —
    # opt-in, the capture burns ~0.25 s of watchdog-thread time per
    # (rate-limited) episode
    stall_profile: bool = False
    # SLO tracker (rpc/telemetry_digest.py SloTracker): S3 availability
    # target (percent of requests answered without a 5xx) and p99
    # latency target, both accounted over a rolling window -> the
    # `slo_error_budget_remaining` / `slo_burn_rate` gauges and the
    # cluster rollup's SLO block
    slo_availability_target: float = 99.9  # percent
    slo_latency_p99_target_msec: float = 1000.0
    slo_window_secs: float = 3600.0
    # latency X-ray (utils/latency.py): phase-level critical-path
    # attribution of S3 requests, served from /v1/debug/latency — on by
    # default, zero external collectors (span-end hook like the flight
    # recorder)
    latency_xray: bool = True
    # canary prober (api/s3/canary.py): low-rate synthetic PUT/GET/DELETE
    # against a hidden bucket so the waterfall, SLO budgets and outlier
    # detector have signal on an idle cluster.  Spawned by the daemon
    # when the S3 API is enabled.
    canary_enabled: bool = True
    canary_interval_secs: float = 60.0
    canary_object_bytes: int = 65536
    # must be a valid S3 bucket name; "hidden" because only the canary's
    # own key is authorized on it (ListBuckets is per-key)
    canary_bucket: str = "canary-probe"
    # traffic observatory (rpc/traffic.py + utils/sketch.py): streaming
    # hot-object / op-mix / skew analytics fed from the S3 request path,
    # served from /v1/traffic (+ /v1/traffic/profile) — on by default,
    # bounded memory (Space-Saving top-K + Count-Min).  The halflife is
    # the exponential-decay window: "hot" means hot over roughly this
    # many seconds, not since process start.
    traffic_observatory: bool = True
    traffic_topk: int = 256
    traffic_halflife_secs: float = 600.0
    # rebalance observatory (rpc/transition.py): |clock offset| above
    # which a node gets the `SKEW!` flag in `cluster top` — beyond it
    # the merged event timeline's ordering is not trustworthy at
    # sub-threshold granularity
    clock_skew_warn_msec: float = 250.0
    # tenant observatory (rpc/tenant.py): per-authenticated-key usage
    # accounting + per-class SLO burn, gossiped as the `tn.*` digest
    # section and federated via /v1/cluster/tenants — on by default,
    # bounded memory (Space-Saving top-K over tenant ids gates exact
    # rows)
    tenant_observatory: bool = True
    tenant_topk: int = 64
    # HOG! threshold: a tenant whose cluster-wide consumption share
    # exceeds this multiple of the fair share (1/tenants) flags in
    # `cluster top` and emits the `tenant-hog` flight event
    tenant_hog_share: float = 3.0


@dataclass
class TenantClassConfig:
    """Rebuild-specific: one `[tenants.<class>]` SLO class for the
    tenant observatory (rpc/tenant.py).  A class names its availability
    and latency targets and lists the access-key ids that belong to it;
    keys not listed anywhere fall to the `default` class (which may
    itself be configured here to override the built-in targets)."""

    # percent of the tenant's requests answered without a 5xx
    availability_target: float = 99.9
    # per-request latency target: requests over it burn the tenant's
    # latency budget (same allowed fraction as availability)
    latency_target_msec: float = 1000.0
    # access-key ids (the AUTHENTICATED identity) in this class
    keys: list[str] = field(default_factory=list)


@dataclass
class ConsulDiscoveryConfig:
    """Reference src/util/config.rs ConsulDiscoveryConfig / consul.rs."""

    consul_http_addr: str = "http://127.0.0.1:8500"
    service_name: str = "garage-tpu"
    api: str = "catalog"  # "catalog" | "agent"
    token: str | None = None
    tags: list[str] = field(default_factory=list)
    meta: dict[str, str] = field(default_factory=dict)
    # TLS to the consul endpoint (reference config.rs ca_cert/client_cert/
    # client_key/tls_skip_verify)
    ca_cert: str | None = None
    client_cert: str | None = None
    client_key: str | None = None
    tls_skip_verify: bool = False


@dataclass
class KubernetesDiscoveryConfig:
    """Reference src/util/config.rs KubernetesDiscoveryConfig / kubernetes.rs."""

    namespace: str = "default"
    service_name: str = "garage-tpu"
    skip_crd: bool = False
    api_server: str | None = None  # None = in-cluster default
    token: str | None = None  # None = mounted service account


@dataclass
class RepairPlanConfig:
    """Rebuild-specific: admission-control defaults for the repair plane
    (block/repair_plan.py) — runtime-tunable via `worker set
    repair-tranquility` / `repair-bytes-in-flight`."""

    tranquility: int = 2  # Tranquilizer pacing between rounds (0 = flat out)
    bytes_in_flight: int = 128 * 1024 * 1024  # surviving-shard bytes / round
    batch_blocks: int | None = None  # None: 2x device mesh, min 256
    auto_resume: bool = True  # resume a checkpointed plan at daemon start


@dataclass
class DurabilityConfig:
    """Rebuild-specific: the durability observatory
    (block/durability.py DurabilityScanner) — an incremental
    rc-tree walk classifying every locally-owned block into redundancy
    classes (healthy / degraded / at_risk / unreadable), deriving
    zone-loss exposure, repair ETA and layout-transition progress.
    `worker set durability-tranquility` / `durability-interval-secs`
    tune the running scanner live."""

    enabled: bool = True
    # Tranquilizer pacing between scan batches (same contract as resync:
    # sleep tranquility x the average batch duration; 0 = flat out)
    tranquility: int = 2
    # rc-tree keys classified per work() iteration
    scan_batch: int = 256
    # seconds between full ledger passes (a layout change kicks one
    # immediately); tests tune this down
    interval_secs: float = 60.0
    # a resync-errored block older than this counts "stuck" rather than
    # "transient" in the ledger (error ages, block/resync.py)
    stuck_error_secs: float = 900.0


@dataclass
class OverloadConfig:
    """Rebuild-specific: the overload-control plane (api/overload.py
    admission controller + rpc/shedding.py SLO-driven shedding ladder).
    Defaults are sized for a single node serving heavy mixed traffic;
    `worker set overload-max-in-flight` tunes the cap live."""

    enabled: bool = True
    # global concurrency cap: requests processing at once on this node
    max_in_flight: int = 256
    # per-access-key token bucket (tokens/sec, burst ceiling)
    key_rate: float = 200.0
    key_burst: float = 400.0
    # per-bucket token bucket — a bucket is a tenant surface too (many
    # keys can hammer one bucket)
    bucket_rate: float = 500.0
    bucket_burst: float = 1000.0
    # LRU bound on tracked tenants (keys + buckets each)
    max_tracked_tenants: int = 1024
    # top tier (interactive GET/HEAD) queues up to this long for
    # capacity instead of shedding; bounded depth
    queue_wait_msec: float = 2000.0
    queue_depth: int = 64
    # Retry-After hint on 503 SlowDown when no better estimate exists
    shed_retry_after_secs: float = 2.0
    # shedding controller (rpc/shedding.py): evaluation cadence and
    # hysteresis thresholds on the max SLO burn rate / loop lag p99
    check_interval_secs: float = 5.0
    ladder_burn_up: float = 2.0  # step up while burn exceeds this
    ladder_burn_down: float = 0.5  # recovery requires burn below this
    loop_lag_p99_msec: float = 500.0  # or event-loop lag p99 over this
    ladder_hold_secs: float = 30.0  # continuous recovery before a step down
    # noise floor: the burn signal only counts once the SLO window holds
    # at least this many requests — one 500 on an idle node must not
    # walk the ladder (mirrors the outlier detector's eps floor)
    min_window_requests: int = 100


@dataclass
class BlockConfig:
    """Rebuild-specific: foreground block-layer tuning — the cross-
    request codec batcher (block/codec_batch.py) and the CPU-offload
    thresholds of the PUT pipeline.  `codec-batch-linger-msec` /
    `codec-batch-max-blocks` tune the live batcher via `worker set`."""

    # cross-request codec batcher (EC write path)
    batch_enabled: bool = True
    # how long a lone block may wait for companions before its dispatch
    # flushes anyway — bounds the single-client latency tax
    batch_linger_msec: float = 2.0
    # a full batch flushes immediately (mesh-sized dispatch ceiling)
    batch_max_blocks: int = 64
    batch_max_bytes: int = 64 * 1024 * 1024
    # dispatch backend: "auto" (device kernel on TPU backends, native
    # host codec on CPU), or force "xla" / "host"
    batch_impl: str = "auto"
    # CPU-bound work this size or larger leaves the event loop
    # (replica-path zstd, content hashing): below it the thread-hop
    # overhead exceeds the stall it avoids
    cpu_offload_min_bytes: int = 64 * 1024
    # EC read path (ISSUE 13, doc/monitoring.md read-path runbook):
    # hot-block cache budget — a bounded-bytes LRU of assembled
    # plaintext blocks per node (0 disables; live `worker set
    # read-cache-bytes`)
    read_cache_bytes: int = 128 * 1024 * 1024
    # hedged reads: when a fetch stays unanswered past an RTT-derived
    # delay (slowest healthy peer's EWMA x mult, floored at min), a
    # hedge launches to the next candidate / a parity rank
    read_hedge_enabled: bool = True
    read_hedge_min_msec: float = 30.0
    read_hedge_rtt_mult: float = 4.0


@dataclass
class MetaConfig:
    """Rebuild-specific knobs for the metadata plane (ISSUE 15): the
    `model/` sharded tables carry their own replication factor — the
    metadata ring, first `replication_factor` distinct nodes of each
    partition's layout node list (table/replication.py
    TableMetaReplication) — so table quorums stay O(1) in EC stripe
    width, plus the table insert coalescer (table/coalesce.py) the
    smaller quorum makes worth having.  `worker set
    meta-coalesce-linger-msec` / `meta-coalesce-max-entries` tune the
    live coalescers."""

    # metadata replication factor.  On layouts whose own rf is SMALLER
    # (replica modes "1"/"2") the ring falls back to the full partition
    # node list — the effective factor is min(this, layout rf).
    replication_factor: int = 3
    # cross-caller coalescing of table inserts: same-destination rows
    # from concurrent requests share one RPC per node (CodecBatcher lane
    # pattern).  A lone insert flushes after the linger; a full batch
    # flushes immediately.
    coalesce_enabled: bool = True
    coalesce_linger_msec: float = 1.0
    coalesce_max_entries: int = 256
    # metadata fast path: per-node LRU of COMPLETE versions' rows —
    # safe because a visible complete version's block list is immutable
    # (model/s3/version_table.py VersionRowCache); 0 disables
    version_cache_entries: int = 1024


@dataclass
class TpuConfig:
    """Rebuild-specific: the TPU compute plane used by the EC block codec and
    batched scrub hashing (no analog in the reference)."""

    enable: bool = True  # use jax backend if available, else numpy fallback
    platform: str | None = None  # force "tpu"/"cpu"; None = jax default
    batch_blocks: int = 1024  # blocks aggregated per EC/hash dispatch
    max_dispatch_bytes: int = 256 * 1024 * 1024  # RAM budget per dispatch


@dataclass
class Config:
    metadata_dir: str = ""
    data_dir: list[DataDir] = field(default_factory=list)

    db_engine: str = "sqlite"  # "sqlite" | "log" | "native" | "memory" (reference: lmdb|sqlite)
    # disabled by default like the reference (src/util/config.rs:19-21
    # "Whether to fsync after all metadata transactions (disabled by
    # default)"): a process crash can't lose committed metadata (the page
    # cache survives), only a host crash can — and quorum replication is
    # the durability story there.  Engine mapping: log/native skip the
    # per-commit fdatasync; sqlite runs WAL+synchronous=NORMAL (sync at
    # checkpoints only) vs FULL when true.
    # Round 4: the native engine also accepts "group" — group commit, a
    # C++ flusher coalesces concurrent commits into shared fdatasyncs
    # (durability window ~ one fdatasync; full sync at barriers).
    metadata_fsync: bool | str = False
    data_fsync: bool = False
    metadata_auto_snapshot_interval: int | None = None  # msec
    metadata_snapshots_dir: str | None = None  # default <metadata_dir>/snapshots
    disable_scrub: bool = False
    use_local_tz: bool = False  # lifecycle worker day boundaries
    allow_punycode: bool = False  # xn-- bucket names/aliases
    # "text" | "json" — JSON-lines output with trace_id/span_id stamping
    # (utils/log_fmt.py); env GARAGE_LOG_FORMAT overrides
    log_format: str = "text"

    block_size: int = DEFAULT_BLOCK_SIZE
    block_ram_buffer_max: int = 256 * 1024 * 1024
    compression_level: int | None = DEFAULT_COMPRESSION_LEVEL  # None = off

    replication_factor: int = 1
    consistency_mode: str = "consistent"  # consistent|degraded|dangerous
    # Rebuild extension: "ec:k:m" selects the erasure-coded block codec;
    # metadata tables always use plain replication_factor.
    replication_mode: str | None = None

    rpc_secret: str | None = None
    rpc_secret_file: str | None = None
    rpc_bind_addr: str = "127.0.0.1:3901"
    rpc_bind_outgoing: bool = False
    rpc_public_addr: str | None = None
    # pick the public address automatically: first local interface address
    # inside this CIDR (reference config.rs rpc_public_addr_subnet)
    rpc_public_addr_subnet: str | None = None
    rpc_timeout_msec: int = 10_000
    rpc_ping_timeout_msec: int | None = None  # default net/peering.PING_TIMEOUT

    bootstrap_peers: list[str] = field(default_factory=list)

    allow_world_readable_secrets: bool = False

    meta: MetaConfig = field(default_factory=MetaConfig)
    s3_api: S3ApiConfig = field(default_factory=S3ApiConfig)
    k2v_api: K2VApiConfig = field(default_factory=K2VApiConfig)
    s3_web: WebConfig = field(default_factory=WebConfig)
    admin: AdminConfig = field(default_factory=AdminConfig)
    block: BlockConfig = field(default_factory=BlockConfig)
    tpu: TpuConfig = field(default_factory=TpuConfig)
    repair: RepairPlanConfig = field(default_factory=RepairPlanConfig)
    durability: DurabilityConfig = field(default_factory=DurabilityConfig)
    overload: OverloadConfig = field(default_factory=OverloadConfig)
    consul_discovery: ConsulDiscoveryConfig | None = None
    kubernetes_discovery: KubernetesDiscoveryConfig | None = None
    # `[tenants.<class>]` SLO classes for the tenant observatory
    # (rpc/tenant.py): class name -> targets + member key ids
    tenants: dict[str, TenantClassConfig] = field(default_factory=dict)

    # --- derived -----------------------------------------------------------

    def ec_params(self) -> tuple[int, int] | None:
        """(k, m) when replication_mode = "ec:k:m", else None."""
        if self.replication_mode and self.replication_mode.startswith("ec:"):
            m = re.fullmatch(r"ec:(\d+):(\d+)", self.replication_mode)
            if not m:
                raise ValueError(
                    f"bad replication_mode {self.replication_mode!r}, want ec:k:m"
                )
            k, mm = int(m.group(1)), int(m.group(2))
            if not (1 <= k <= 128 and 1 <= mm <= 128 and k + mm <= 255):
                raise ValueError("ec:k:m out of range (k+m must be <= 255)")
            return (k, mm)
        return None


def _get_secret(
    inline: str | None, file_path: str | None, env_name: str, allow_world_readable: bool
) -> str | None:
    """Secret layering (reference src/garage/secrets.rs): env overrides;
    inline + file together is an ambiguous config and refused
    (secrets.rs:98 "only one of `x` and `x_file` can be set"); file must
    not be world-readable."""
    if inline and file_path:
        raise ValueError(
            f"only one of the inline secret and its _file variant may be "
            f"set (env {env_name})"
        )
    env = os.environ.get(env_name)
    if env:
        return env.strip()
    if file_path:
        st = os.stat(file_path)
        # refuse any group/other access bits (reference src/garage/secrets.rs:128)
        if st.st_mode & 0o077 and not allow_world_readable:
            raise ValueError(
                f"secret file {file_path} is accessible by group/others "
                f"(mode {st.st_mode & 0o777:o}); refusing "
                "(set allow_world_readable_secrets = true to override)"
            )
        with open(file_path) as f:
            return f.read().strip()
    return inline


def _parse_toml_minimal(text: str) -> dict[str, Any]:
    """Fallback TOML-subset parser for interpreters without tomllib
    (python < 3.11 containers): comments, [dotted.sections], and
    `key = value` with string / int / float / bool / single-line array
    values — the full shape of garage config files.  Anything fancier
    raises rather than guessing."""

    def scalar(tok: str):
        tok = tok.strip()
        if len(tok) >= 2 and tok[0] == tok[-1] and tok[0] in "\"'":
            body = tok[1:-1]
            if tok[0] == '"':
                body = (
                    body.replace("\\\\", "\x00")
                    .replace('\\"', '"')
                    .replace("\\n", "\n")
                    .replace("\\t", "\t")
                    .replace("\x00", "\\")
                )
            return body
        if tok in ("true", "false"):
            return tok == "true"
        try:
            return int(tok)
        except ValueError:
            pass
        try:
            return float(tok)
        except ValueError:
            raise ValueError(f"unsupported TOML value {tok!r}") from None

    def split_csv(body: str) -> list[str]:
        out, cur, quote = [], "", None
        for ch in body:
            if quote:
                cur += ch
                if ch == quote and not cur.endswith("\\" + quote):
                    quote = None
            elif ch in "\"'":
                quote = ch
                cur += ch
            elif ch == ",":
                out.append(cur)
                cur = ""
            else:
                cur += ch
        if cur.strip():
            out.append(cur)
        return out

    root: dict[str, Any] = {}
    table = root
    for lineno, raw_line in enumerate(text.splitlines(), 1):
        line = raw_line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            if not line.endswith("]") or line.startswith("[["):
                raise ValueError(f"line {lineno}: unsupported section {line!r}")
            table = root
            for part in line[1:-1].strip().split("."):
                table = table.setdefault(part.strip(), {})
            continue
        key, eq, val = line.partition("=")
        if not eq:
            raise ValueError(f"line {lineno}: expected key = value, got {line!r}")
        key = key.strip()
        target = table
        if key.startswith('"') and key.endswith('"'):
            key = key[1:-1]  # quoted key: dots are literal
        elif "." in key:
            # dotted key nests, exactly like tomllib ('a.b = 1' ->
            # {'a': {'b': 1}}) — storing the literal "a.b" would make the
            # same file parse differently on py3.11 vs the fallback
            *parents, key = [part.strip().strip('"') for part in key.split(".")]
            for part in parents:
                target = target.setdefault(part, {})
        val = val.strip()
        # strip a trailing comment: first '#' OUTSIDE any quoted string
        quote = None
        for i, ch in enumerate(val):
            if quote:
                if ch == quote and val[i - 1] != "\\":
                    quote = None
            elif ch in "\"'":
                quote = ch
            elif ch == "#":
                val = val[:i].strip()
                break
        if val.startswith("["):
            if not val.endswith("]"):
                raise ValueError(
                    f"line {lineno}: multi-line arrays need python >= 3.11"
                )
            target[key] = [scalar(t) for t in split_csv(val[1:-1])]
        else:
            target[key] = scalar(val)
    return root


def read_config(path: str) -> Config:
    # the loop is not serving traffic before the config exists
    if tomllib is not None:
        # graft-lint: allow-blocking(startup-only config read)
        with open(path, "rb") as f:
            raw = tomllib.load(f)
    else:
        # graft-lint: allow-blocking(startup-only config read)
        with open(path, encoding="utf-8") as f:
            raw = _parse_toml_minimal(f.read())
    return config_from_dict(raw)


def config_from_dict(raw: dict[str, Any]) -> Config:
    cfg = Config()
    simple = {
        f
        for f in (
            "metadata_dir db_engine metadata_fsync data_fsync block_size "
            "block_ram_buffer_max replication_factor consistency_mode "
            "replication_mode rpc_secret rpc_secret_file rpc_bind_addr "
            "rpc_bind_outgoing rpc_public_addr rpc_public_addr_subnet "
            "rpc_timeout_msec rpc_ping_timeout_msec "
            "bootstrap_peers allow_world_readable_secrets "
            "metadata_auto_snapshot_interval metadata_snapshots_dir "
            "disable_scrub use_local_tz allow_punycode log_format"
        ).split()
    }
    for k, v in raw.items():
        if k in simple:
            setattr(cfg, k, v)
        elif k == "compression_level":
            # "none" disables; any integer (incl. 0) is a zstd level
            # (reference src/util/config.rs:288-315)
            if v == "none":
                cfg.compression_level = None
            elif isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(f"bad compression_level {v!r}")
            else:
                cfg.compression_level = v
        elif k == "data_dir":
            if isinstance(v, str):
                cfg.data_dir = [DataDir(path=v)]
            else:
                cfg.data_dir = [
                    DataDir(
                        path=d["path"],
                        capacity=_parse_capacity(d.get("capacity")),
                        read_only=bool(d.get("read_only", False)),
                    )
                    for d in v
                ]
        elif k == "meta":
            cfg.meta = MetaConfig(**_known(v, MetaConfig))
        elif k == "s3_api":
            cfg.s3_api = S3ApiConfig(**_known(v, S3ApiConfig))
        elif k == "k2v_api":
            cfg.k2v_api = K2VApiConfig(**_known(v, K2VApiConfig))
        elif k == "s3_web":
            cfg.s3_web = WebConfig(**_known(v, WebConfig))
        elif k == "admin":
            cfg.admin = AdminConfig(**_known(v, AdminConfig))
        elif k == "block":
            cfg.block = BlockConfig(**_known(v, BlockConfig))
        elif k == "tpu":
            cfg.tpu = TpuConfig(**_known(v, TpuConfig))
        elif k == "repair":
            cfg.repair = RepairPlanConfig(**_known(v, RepairPlanConfig))
        elif k == "durability":
            cfg.durability = DurabilityConfig(**_known(v, DurabilityConfig))
        elif k == "overload":
            cfg.overload = OverloadConfig(**_known(v, OverloadConfig))
        elif k == "consul_discovery":
            cfg.consul_discovery = ConsulDiscoveryConfig(
                **_known(v, ConsulDiscoveryConfig)
            )
        elif k == "kubernetes_discovery":
            cfg.kubernetes_discovery = KubernetesDiscoveryConfig(
                **_known(v, KubernetesDiscoveryConfig)
            )
        elif k == "tenants":
            if not isinstance(v, dict):
                raise ValueError(
                    "[tenants] must be a table of [tenants.<class>] "
                    "sections"
                )
            cfg.tenants = {
                str(name): TenantClassConfig(**_known(tc, TenantClassConfig))
                for name, tc in v.items()
            }
        # unknown sections are ignored (forward compat)
    # metadata_fsync is tri-state, not stringly-typed: anything else (a
    # "goup" typo, "yes", 2) used to fall through as a truthy value and
    # silently select per-commit sync — validate at load, fail loudly
    if cfg.metadata_fsync not in (True, False, "group"):
        raise ValueError(
            f"invalid metadata_fsync {cfg.metadata_fsync!r}: accepted values "
            'are true, false, or "group" (group commit, native engine only)'
        )
    # SLO knobs: a target of 100.0 would make the allowed-error fraction
    # zero (every request burns infinite budget) — refuse the footgun at
    # load time along with plainly-invalid values
    if not (0.0 < float(cfg.admin.slo_availability_target) < 100.0):
        raise ValueError(
            f"invalid slo_availability_target "
            f"{cfg.admin.slo_availability_target!r}: want a percentage in "
            "(0, 100), e.g. 99.9"
        )
    if float(cfg.admin.slo_latency_p99_target_msec) <= 0:
        raise ValueError("slo_latency_p99_target_msec must be > 0")
    if float(cfg.admin.slo_window_secs) <= 0:
        raise ValueError("slo_window_secs must be > 0")
    # canary knobs: an interval of 0 would busy-loop synthetic traffic
    # through the full S3 stack; an empty bucket name can't be created
    if float(cfg.admin.canary_interval_secs) <= 0:
        raise ValueError("canary_interval_secs must be > 0")
    if int(cfg.admin.canary_object_bytes) < 1:
        raise ValueError("canary_object_bytes must be >= 1")
    if not str(cfg.admin.canary_bucket).strip():
        raise ValueError("canary_bucket must be a non-empty bucket name")
    # traffic observatory: a tiny top-K can't rank anything, a zero/
    # negative halflife breaks the decay math at the first sweep
    if int(cfg.admin.traffic_topk) < 8:
        raise ValueError("traffic_topk must be >= 8")
    if float(cfg.admin.traffic_halflife_secs) <= 0:
        raise ValueError("traffic_halflife_secs must be > 0")
    # rebalance observatory: a non-positive skew threshold would flag
    # every node SKEW! on the first status exchange
    if float(cfg.admin.clock_skew_warn_msec) <= 0:
        raise ValueError("clock_skew_warn_msec must be > 0")
    # tenant observatory: a tiny top-K can't rank anything; a hog
    # threshold below 1 would flag tenants consuming LESS than their
    # fair share
    if int(cfg.admin.tenant_topk) < 8:
        raise ValueError("tenant_topk must be >= 8")
    if float(cfg.admin.tenant_hog_share) < 1:
        raise ValueError("tenant_hog_share must be >= 1")
    # `[tenants.<class>]` SLO classes: same footguns as the global slo_*
    # knobs — a 100% availability target makes the allowed-error
    # fraction zero, and a key id claimed by two classes would make
    # per-tenant burn depend on dict iteration order
    seen_keys: dict[str, str] = {}
    for name, tc in cfg.tenants.items():
        if not str(name).strip():
            raise ValueError("[tenants] class names must be non-empty")
        # class names become a metric LABEL value (api_tenant_class_*):
        # the shape contract enrolled in BOUNDED_LABEL_VALUES
        # (script/dashboard_lint.py) is enforced here, at config load
        if not re.fullmatch(r"[a-zA-Z0-9][a-zA-Z0-9_.\-]{0,63}", str(name)):
            raise ValueError(
                f"invalid tenants class name {name!r}: want "
                "[a-zA-Z0-9][a-zA-Z0-9_.-]{0,63}"
            )
        if not (0.0 < float(tc.availability_target) < 100.0):
            raise ValueError(
                f"invalid tenants.{name}.availability_target "
                f"{tc.availability_target!r}: want a percentage in "
                "(0, 100), e.g. 99.9"
            )
        if float(tc.latency_target_msec) <= 0:
            raise ValueError(
                f"tenants.{name}.latency_target_msec must be > 0"
            )
        for kid in tc.keys or []:
            other = seen_keys.get(kid)
            if other is not None:
                raise ValueError(
                    f"key {kid!r} listed in both tenant classes "
                    f"{other!r} and {name!r}"
                )
            seen_keys[kid] = str(name)
    # durability observatory knobs: a zero batch can never finish a
    # pass, a non-positive interval busy-loops full rc-tree walks
    du = cfg.durability
    if int(du.scan_batch) < 1:
        raise ValueError("durability.scan_batch must be >= 1")
    if float(du.interval_secs) <= 0:
        raise ValueError("durability.interval_secs must be > 0")
    if int(du.tranquility) < 0:
        raise ValueError("durability.tranquility must be >= 0")
    if float(du.stuck_error_secs) <= 0:
        raise ValueError("durability.stuck_error_secs must be > 0")
    # overload knobs: refuse values that would wedge admission at load
    # time (a zero rate admits nothing forever; inverted hysteresis
    # thresholds would make the ladder oscillate by construction)
    ov = cfg.overload
    if int(ov.max_in_flight) < 1:
        raise ValueError("overload.max_in_flight must be >= 1")
    for knob in ("key_rate", "bucket_rate"):
        if float(getattr(ov, knob)) <= 0:
            raise ValueError(f"overload.{knob} must be > 0")
    # a burst below 1 caps the bucket under one whole token: take(1)
    # can never succeed and every tenant wedges permanently
    for knob in ("key_burst", "bucket_burst"):
        if float(getattr(ov, knob)) < 1:
            raise ValueError(f"overload.{knob} must be >= 1")
    if float(ov.queue_wait_msec) < 0 or int(ov.queue_depth) < 0:
        raise ValueError("overload queue_wait_msec/queue_depth must be >= 0")
    if not (0 <= float(ov.ladder_burn_down) < float(ov.ladder_burn_up)):
        raise ValueError(
            "overload.ladder_burn_down must be < ladder_burn_up (hysteresis)"
        )
    if float(ov.check_interval_secs) <= 0 or float(ov.ladder_hold_secs) <= 0:
        raise ValueError(
            "overload check_interval_secs/ladder_hold_secs must be > 0"
        )
    if float(ov.loop_lag_p99_msec) <= 0:
        raise ValueError("overload.loop_lag_p99_msec must be > 0")
    # block-layer batching knobs: refuse values that would wedge the
    # batcher at load time (a zero-block batch cap can never dispatch;
    # a negative linger is a time-travel request)
    blk = cfg.block
    if float(blk.batch_linger_msec) < 0:
        raise ValueError("block.batch_linger_msec must be >= 0")
    if int(blk.batch_max_blocks) < 1:
        raise ValueError("block.batch_max_blocks must be >= 1")
    if int(blk.batch_max_bytes) < 1:
        raise ValueError("block.batch_max_bytes must be >= 1")
    if blk.batch_impl not in ("auto", "host", "xla"):
        raise ValueError(
            f"invalid block.batch_impl {blk.batch_impl!r}: "
            'want "auto", "host" or "xla"'
        )
    if int(blk.cpu_offload_min_bytes) < 0:
        raise ValueError("block.cpu_offload_min_bytes must be >= 0")
    # read-path knobs (ISSUE 13): a negative cache budget is nonsense
    # (0 = disabled is fine); a zero/negative hedge multiplier would
    # hedge every read unconditionally the moment any EWMA exists
    if int(blk.read_cache_bytes) < 0:
        raise ValueError("block.read_cache_bytes must be >= 0")
    if float(blk.read_hedge_min_msec) < 0:
        raise ValueError("block.read_hedge_min_msec must be >= 0")
    if float(blk.read_hedge_rtt_mult) <= 0:
        raise ValueError("block.read_hedge_rtt_mult must be > 0")
    # resolve secrets
    cfg.rpc_secret = _get_secret(
        cfg.rpc_secret,
        cfg.rpc_secret_file,
        "GARAGE_RPC_SECRET",
        cfg.allow_world_readable_secrets,
    )
    cfg.admin.admin_token = _get_secret(
        cfg.admin.admin_token,
        cfg.admin.admin_token_file,
        "GARAGE_ADMIN_TOKEN",
        cfg.allow_world_readable_secrets,
    )
    cfg.admin.metrics_token = _get_secret(
        cfg.admin.metrics_token,
        cfg.admin.metrics_token_file,
        "GARAGE_METRICS_TOKEN",
        cfg.allow_world_readable_secrets,
    )
    # parity with reference legacy replication_mode values
    # ("1"|"2"|"3"|"2-dangerous"|"3-degraded"|"3-dangerous",
    #  src/rpc/replication_mode.rs:74-80); "ec:k:m" is the rebuild extension
    if cfg.replication_mode and not cfg.replication_mode.startswith("ec:"):
        legacy = {
            "1": (1, "consistent"),
            "2": (2, "consistent"),
            "2-dangerous": (2, "dangerous"),
            "3": (3, "consistent"),
            "3-degraded": (3, "degraded"),
            "3-dangerous": (3, "dangerous"),
        }
        if cfg.replication_mode not in legacy:
            raise ValueError(
                f"invalid replication_mode {cfg.replication_mode!r} "
                "(want 1|2|3[-degraded|-dangerous] or ec:k:m)"
            )
        cfg.replication_factor, cfg.consistency_mode = legacy[cfg.replication_mode]
        cfg.replication_mode = None
    ec = cfg.ec_params()  # validates ec:k:m syntax at parse time
    if ec is not None:
        # every block needs k+m distinct nodes: the layout's replication
        # factor IS the stripe width (shard placement constraint).  An
        # explicitly configured mismatching value is an error, not a
        # silent override (it would change metadata quorums invisibly).
        k, m = ec
        if "replication_factor" in raw and cfg.replication_factor != k + m:
            raise ValueError(
                f"replication_mode {cfg.replication_mode!r} requires "
                f"replication_factor = {k + m} (or omit it); got "
                f"{cfg.replication_factor}"
            )
        cfg.replication_factor = k + m
    # metadata plane (ISSUE 15): validated AFTER the mode resolution
    # above so cfg.replication_factor is final.  The layout needs at
    # least `replication_factor` storage nodes, so that is the smallest
    # cluster this config can run — an EXPLICIT meta factor above it
    # could never place its ring and is a config error, not a silent
    # runtime clamp.  The unconfigured default (3) clamps instead
    # (replica modes "1"/"2" fall back to the full partition node list,
    # table/replication.py).
    mt = cfg.meta
    if int(mt.replication_factor) < 1:
        raise ValueError("meta.replication_factor must be >= 1")
    if (
        "meta" in raw
        and "replication_factor" in raw["meta"]
        and int(mt.replication_factor) > cfg.replication_factor
    ):
        raise ValueError(
            f"meta.replication_factor {mt.replication_factor} exceeds the "
            f"cluster replication factor {cfg.replication_factor} (the "
            "minimum cluster size): the metadata ring could never place "
            f"{mt.replication_factor} distinct replicas"
        )
    if float(mt.coalesce_linger_msec) < 0:
        raise ValueError("meta.coalesce_linger_msec must be >= 0")
    if int(mt.coalesce_max_entries) < 1:
        raise ValueError("meta.coalesce_max_entries must be >= 1")
    if int(mt.version_cache_entries) < 0:
        raise ValueError("meta.version_cache_entries must be >= 0")
    return cfg


def _known(d: dict[str, Any], cls: type) -> dict[str, Any]:
    fields = cls.__dataclass_fields__  # type: ignore[attr-defined]
    return {k: v for k, v in d.items() if k in fields}


_CAP_RE = re.compile(r"^\s*([0-9.]+)\s*([kKmMgGtT]?)(i?)[bB]?\s*$")
_CAP_DEC = {"": 1, "k": 10**3, "m": 10**6, "g": 10**9, "t": 10**12}
_CAP_BIN = {"": 1, "k": 2**10, "m": 2**20, "g": 2**30, "t": 2**40}


def _parse_capacity(v: Any) -> int | None:
    """'1T' = 10^12, '1TiB' = 2^40 — same semantics as the reference's
    bytesize crate (decimal for plain suffix, binary for the 'i' forms)."""
    if v is None:
        return None
    if isinstance(v, int):
        return v
    m = _CAP_RE.match(str(v))
    if not m:
        raise ValueError(f"bad capacity {v!r}")
    mult = (_CAP_BIN if m.group(3) else _CAP_DEC)[m.group(2).lower()]
    return int(float(m.group(1)) * mult)
