"""Time helpers (reference src/util/time.rs)."""

from __future__ import annotations

import time
from datetime import datetime, timezone


def now_msec() -> int:
    """Milliseconds since the unix epoch."""
    return int(time.time() * 1000)


def increment_logical_clock(prev: int) -> int:
    """max(now, prev+1) — monotone timestamps for LWW registers
    (reference src/util/time.rs:9-13)."""
    return max(now_msec(), prev + 1)


def msec_to_rfc3339(msecs: int) -> str:
    dt = datetime.fromtimestamp(msecs / 1000.0, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{msecs % 1000:03d}Z"
