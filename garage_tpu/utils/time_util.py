"""Time helpers (reference src/util/time.rs)."""

from __future__ import annotations

import time
from datetime import datetime, timezone


# fault-injection seam (chaos/jepsen clock nemesis): a test shifts the
# whole process's notion of wall time to exercise the LWW/next_timestamp
# logic under forward and BACKWARD clock jumps
_offset_msec = 0


def set_clock_offset(ms: int) -> None:
    global _offset_msec
    _offset_msec = ms


def now_msec() -> int:
    """Milliseconds since the unix epoch."""
    return int(time.time() * 1000) + _offset_msec


def increment_logical_clock(prev: int) -> int:
    """max(now, prev+1) — monotone timestamps for LWW registers
    (reference src/util/time.rs:9-13)."""
    return max(now_msec(), prev + 1)


def msec_to_rfc3339(msecs: int) -> str:
    dt = datetime.fromtimestamp(msecs / 1000.0, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{msecs % 1000:03d}Z"
