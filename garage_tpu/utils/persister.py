"""Atomic save/load of versioned values to a file.

Mirrors reference src/util/persister.rs:10-112: write to a temp file in the
same directory, fsync, rename over the target — so a crash never leaves a
half-written state file.
"""

from __future__ import annotations

import os
from typing import Generic, TypeVar

from .migrate import Migratable

T = TypeVar("T", bound=Migratable)


class Persister(Generic[T]):
    def __init__(self, directory: str, name: str, typ: type[T]):
        self.path = os.path.join(directory, name)
        self.typ = typ

    def load(self) -> T | None:
        try:
            with open(self.path, "rb") as f:
                return self.typ.decode(f.read())
        except FileNotFoundError:
            return None

    def save(self, value: T) -> None:
        data = value.encode()
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load_raw(self) -> bytes | None:
        try:
            with open(self.path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def save_raw(self, data: bytes) -> None:
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
