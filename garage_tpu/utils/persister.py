"""Atomic save/load of versioned values to a file.

Mirrors reference src/util/persister.rs:10-112: write to a temp file in the
same directory, fsync, rename over the target — so a crash never leaves a
half-written state file.
"""

from __future__ import annotations

import os
from typing import Generic, TypeVar

from .migrate import Migratable

T = TypeVar("T", bound=Migratable)


class Persister(Generic[T]):
    def __init__(self, directory: str, name: str, typ: type[T]):
        self.path = os.path.join(directory, name)
        self.typ = typ
        # serializes _write_raw across the loop thread (sync save from
        # operator one-shots) and save_in_thread's worker thread: both
        # share one <path>.tmp, and an unsynchronized second open("wb")
        # would truncate it mid-write
        import threading

        self._write_mu = threading.Lock()

    def load(self) -> T | None:
        try:
            with open(self.path, "rb") as f:
                return self.typ.decode(f.read())
        except FileNotFoundError:
            return None

    def save(self, value: T) -> None:
        self._write_raw(value.encode())

    async def save_in_thread(self, value: T) -> None:
        """Checkpoint from a coroutine: encode ON the loop thread (the
        value may be mutated by other coroutines — a thread-side encode
        would race it), then run the write/fsync/rename in a worker
        thread so the disk flush never stalls the event loop
        (graft-lint loop-blocker, surfaced by the ISSUE 10 deeper
        receiver resolution)."""
        import asyncio

        data = value.encode()
        await asyncio.to_thread(self._write_raw, data)

    def _write_raw(self, data: bytes) -> None:
        with self._write_mu:
            tmp = self.path + ".tmp"
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    def load_raw(self) -> bytes | None:
        try:
            with open(self.path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def save_raw(self, data: bytes) -> None:
        self._write_raw(data)
