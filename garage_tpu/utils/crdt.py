"""Conflict-free replicated data types.

Mirrors reference src/util/crdt/ (mod.rs:12-26): the `Crdt` trait with an
idempotent, commutative, associative `merge`, and the standard instances the
table schemas are built from: `Lww`, `LwwMap`, `Map`, `Bool`, `Deletable`.

Values stored inside CRDTs must be msgpack-encodable trees (or themselves
CRDTs for `Map`/`Deletable`).  Where the reference relies on `Ord` to break
ties deterministically, we order by the msgpack encoding of the value, which
is a total order on encodable values and identical on every node.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Generic, Iterator, TypeVar

from .serde import pack as _serde_pack

T = TypeVar("T")


def _ord_key(v: Any) -> bytes:
    """Deterministic total order for tie-breaking, same on all nodes."""
    if isinstance(v, Crdt):
        v = v.to_obj()
    return _serde_pack(v)


def _adopt(v: Any) -> Any:
    """Deep-copy a value taken from the other side of a merge.

    Rust gets this for free from clone-on-merge; without it, the merged-into
    object and the mutator would alias the same mutable value, so a later
    local edit would silently corrupt an update object the caller may
    re-broadcast (the `update_mutator` pattern)."""
    if isinstance(v, (bytes, str, int, float, bool, type(None))):
        return v
    return copy.deepcopy(v)


class Crdt:
    """Base CRDT: in-place merge; must be idempotent/commutative/associative."""

    def merge(self, other: "Crdt") -> None:
        raise NotImplementedError

    # msgpack-tree serialization
    def to_obj(self) -> Any:
        raise NotImplementedError

    @classmethod
    def from_obj(cls, obj: Any) -> "Crdt":
        raise NotImplementedError


def merge_values(a: Any, b: Any) -> Any:
    """Merge two values that may be CRDTs or plain comparable values.

    Plain values follow AutoCrdt semantics (reference src/util/crdt/mod.rs
    `AutoCrdt`): if they differ, keep the larger in the deterministic order.
    """
    if isinstance(a, Crdt):
        a.merge(b)
        return a
    if a == b:
        return a
    return _adopt(b) if _ord_key(b) > _ord_key(a) else a


class Lww(Crdt, Generic[T]):
    """Last-writer-wins register (reference src/util/crdt/lww.rs).

    Ties on timestamp are broken by the deterministic value order; the inner
    value is itself CRDT-merged when timestamps and order keys are equal.
    """

    __slots__ = ("ts", "value")

    def __init__(self, value: T, ts: int | None = None):
        from .time_util import now_msec

        self.ts = now_msec() if ts is None else ts
        self.value = value

    @classmethod
    def raw(cls, ts: int, value: T) -> "Lww[T]":
        return cls(value, ts=ts)

    def get(self) -> T:
        return self.value

    def update(self, value: T) -> None:
        """Set a new value with a timestamp strictly above the current one."""
        from .time_util import increment_logical_clock

        self.ts = increment_logical_clock(self.ts)
        self.value = value

    def merge(self, other: "Lww[T]") -> None:
        if other.ts > self.ts:
            self.ts, self.value = other.ts, _adopt(other.value)
        elif other.ts == self.ts:
            if isinstance(self.value, Crdt):
                self.value.merge(other.value)
            elif _ord_key(other.value) > _ord_key(self.value):
                self.value = _adopt(other.value)

    def to_obj(self) -> Any:
        v = self.value.to_obj() if isinstance(self.value, Crdt) else self.value
        return [self.ts, v]

    @classmethod
    def from_obj(cls, obj: Any, value_from: Callable[[Any], T] | None = None) -> "Lww[T]":
        ts, v = obj
        return cls(value_from(v) if value_from else v, ts=ts)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Lww)
            and self.ts == other.ts
            and _ord_key(self.value) == _ord_key(other.value)
        )

    def __repr__(self) -> str:
        return f"Lww(ts={self.ts}, value={self.value!r})"


class LwwMap(Crdt, Generic[T]):
    """Map of independent LWW registers, stored as a sorted assoc list
    (reference src/util/crdt/lww_map.rs)."""

    __slots__ = ("vals",)

    def __init__(self, vals: list[tuple[Any, int, T]] | None = None):
        self.vals = sorted(vals or [], key=lambda kv: _ord_key(kv[0]))

    def get(self, k: Any) -> T | None:
        for key, _ts, v in self.vals:
            if key == k:
                return v
        return None

    def get_timestamp(self, k: Any) -> int:
        for key, ts, _v in self.vals:
            if key == k:
                return ts
        return 0

    def update_in_place(self, k: Any, v: T) -> None:
        """Insert/overwrite with a fresh monotone timestamp."""
        from .time_util import increment_logical_clock

        ts = increment_logical_clock(self.get_timestamp(k))
        self.merge(LwwMap([(k, ts, v)]))

    def update_mutator(self, k: Any, v: T) -> "LwwMap[T]":
        """A single-entry LwwMap that, merged in, performs the update."""
        from .time_util import increment_logical_clock

        ts = increment_logical_clock(self.get_timestamp(k))
        return LwwMap([(k, ts, v)])

    def remove(self, k: Any) -> None:
        self.vals = [e for e in self.vals if e[0] != k]

    def items(self) -> list[tuple[Any, T]]:
        return [(k, v) for k, _ts, v in self.vals]

    def merge(self, other: "LwwMap[T]") -> None:
        out: dict[bytes, tuple[Any, int, T]] = {_ord_key(k): (k, ts, v) for k, ts, v in self.vals}
        for k, ts, v in other.vals:
            kk = _ord_key(k)
            cur = out.get(kk)
            if cur is None or ts > cur[1]:
                out[kk] = (k, ts, _adopt(v))
            elif ts == cur[1]:
                # timestamp tie: CRDT-merge the two values (reference
                # lww_map.rs merge_raw, Ordering::Equal branch)
                out[kk] = (k, ts, merge_values(cur[2], v))
        self.vals = [out[kk] for kk in sorted(out)]

    def to_obj(self) -> Any:
        return [
            [k, ts, v.to_obj() if isinstance(v, Crdt) else v] for k, ts, v in self.vals
        ]

    @classmethod
    def from_obj(cls, obj: Any, value_from: Callable[[Any], T] | None = None) -> "LwwMap[T]":
        return cls(
            [(k, ts, value_from(v) if value_from else v) for k, ts, v in obj]
        )

    def __len__(self) -> int:
        return len(self.vals)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, LwwMap) and self.to_obj() == other.to_obj()

    def __repr__(self) -> str:
        return f"LwwMap({self.vals!r})"


class CrdtMap(Crdt, Generic[T]):
    """Map whose values are themselves CRDTs, merged key-wise
    (reference src/util/crdt/map.rs)."""

    __slots__ = ("vals",)

    def __init__(self, vals: list[tuple[Any, T]] | None = None):
        self.vals = sorted(vals or [], key=lambda kv: _ord_key(kv[0]))

    def get(self, k: Any) -> T | None:
        for key, v in self.vals:
            if key == k:
                return v
        return None

    def put(self, k: Any, v: T) -> None:
        self.merge(CrdtMap([(k, v)]))

    def items(self) -> list[tuple[Any, T]]:
        return list(self.vals)

    def merge(self, other: "CrdtMap[T]") -> None:
        out: dict[bytes, tuple[Any, T]] = {_ord_key(k): (k, v) for k, v in self.vals}
        for k, v in other.vals:
            kk = _ord_key(k)
            if kk in out:
                out[kk] = (k, merge_values(out[kk][1], v))
            else:
                out[kk] = (k, _adopt(v))
        self.vals = [out[kk] for kk in sorted(out)]

    def to_obj(self) -> Any:
        return [[k, v.to_obj() if isinstance(v, Crdt) else v] for k, v in self.vals]

    @classmethod
    def from_obj(cls, obj: Any, value_from: Callable[[Any], T] | None = None) -> "CrdtMap[T]":
        return cls([(k, value_from(v) if value_from else v) for k, v in obj])

    def __len__(self) -> int:
        return len(self.vals)

    def __iter__(self) -> Iterator[tuple[Any, T]]:
        return iter(self.vals)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, CrdtMap) and self.to_obj() == other.to_obj()


class Bool(Crdt):
    """OR-merged boolean; used for tombstone `deleted` flags
    (reference src/util/crdt/bool.rs)."""

    __slots__ = ("value",)

    def __init__(self, value: bool = False):
        self.value = bool(value)

    def get(self) -> bool:
        return self.value

    def set(self) -> None:
        self.value = True

    def merge(self, other: "Bool") -> None:
        self.value = self.value or other.value

    def to_obj(self) -> Any:
        return self.value

    @classmethod
    def from_obj(cls, obj: Any) -> "Bool":
        return cls(bool(obj))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Bool) and self.value == other.value

    def __repr__(self) -> str:
        return f"Bool({self.value})"


class Deletable(Crdt, Generic[T]):
    """Present(inner CRDT) | Deleted, deletion winning
    (reference src/util/crdt/deletable.rs)."""

    __slots__ = ("inner",)

    def __init__(self, inner: T | None):
        self.inner = inner

    @classmethod
    def present(cls, v: T) -> "Deletable[T]":
        return cls(v)

    @classmethod
    def deleted(cls) -> "Deletable[T]":
        return cls(None)

    def is_deleted(self) -> bool:
        return self.inner is None

    def get(self) -> T | None:
        return self.inner

    def merge(self, other: "Deletable[T]") -> None:
        if other.inner is None:
            self.inner = None
        elif self.inner is not None:
            self.inner = merge_values(self.inner, other.inner)
        # note: Present never resurrects a Deleted (deletion wins)

    def to_obj(self) -> Any:
        if self.inner is None:
            return None
        return [self.inner.to_obj() if isinstance(self.inner, Crdt) else self.inner]

    @classmethod
    def from_obj(cls, obj: Any, value_from: Callable[[Any], T] | None = None) -> "Deletable[T]":
        if obj is None:
            return cls(None)
        (v,) = obj
        return cls(value_from(v) if value_from else v)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Deletable) and self.to_obj() == other.to_obj()
