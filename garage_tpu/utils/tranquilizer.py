"""Self-throttling for background workers.

Mirrors reference src/util/tranquilizer.rs:9-26: measure the duration of each
work unit over a sliding window; after each unit, sleep
`tranquility × avg(observed durations)` so a worker with tranquility t uses
at most 1/(t+1) of one CPU / disk stream.
"""

from __future__ import annotations

import time
from collections import deque


class Tranquilizer:
    def __init__(self, window: int = 10):
        self.observations: deque[float] = deque(maxlen=window)
        self._last_start: float | None = None

    def reset(self) -> None:
        self._last_start = time.monotonic()

    def tranquilize_delay(self, tranquility: int) -> float:
        """Record the unit that began at `reset()`; return seconds to sleep."""
        if self._last_start is None:
            return 0.0
        dt = time.monotonic() - self._last_start
        self.observations.append(dt)
        self._last_start = None
        if tranquility <= 0 or not self.observations:
            return 0.0
        avg = sum(self.observations) / len(self.observations)
        return min(tranquility * avg, 10.0)
