"""Aligned text tables for CLI output (reference src/format-table/lib.rs:
rows are TAB-separated strings, columns padded to the widest cell)."""

from __future__ import annotations


def format_table(rows: list[str]) -> str:
    split = [r.split("\t") for r in rows]
    if not split:
        return ""
    ncols = max(len(r) for r in split)
    widths = [0] * ncols
    for r in split:
        for i, cell in enumerate(r):
            widths[i] = max(widths[i], len(cell))
    out = []
    for r in split:
        out.append(
            "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(r)).rstrip()
        )
    return "\n".join(out)
