"""Flagship compute pipeline: fused EC coding + BLAKE3 shard hashing.

This is the "model" the bench and graft entry drive: one XLA dispatch that
takes a batch of blocks (split into k data shards each) and produces the m
parity shards plus the 32-byte integrity hash of every one of the k+m
shards — the write-path and scrub/repair hot math of the erasure-coded
block store (BASELINE.json north star), with no host round-trips inside.

Multi-chip: the batch dimension shards over a `Mesh` ("blocks" axis); the
only cross-device communication is a tiny psum of scrub statistics, so the
pipeline scales linearly over ICI (pod-level repair fan-out).
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops import gf
from ..ops.hash_tpu import blake3_batch_fn


class ScrubRepairPipeline:
    """EC(k, m) + shard hashing, fixed shard size, batched over blocks.

    shard_bytes must be a supported BLAKE3 batch length (multiple of 64 up
    to 1024, or a power-of-two number of KiB) — the block layer pads shards
    to these sizes.
    """

    def __init__(self, k: int = 8, m: int = 3, shard_bytes: int = 128 * 1024):
        self.k, self.m, self.shard_bytes = k, m, shard_bytes
        self._enc_bitmat_np = gf.bitmatrix_of(gf.cauchy_parity_matrix(k, m))
        # build lazily so importing this module never touches jax
        self._fns: dict = {}

    # --- single-device fns --------------------------------------------------

    def encode_and_hash_fn(self):
        """Jittable fn: data (B, k, S) uint8 -> (parity (B, m, S),
        hashes (B, k+m, 32), scrub_stats (2,)).

        One fused body serves both the single-device and the mesh step:
        `nvalid` masks zero-pad blocks out of the scrub statistics (the
        single-device wrapper passes the full batch)."""
        import jax.numpy as jnp

        from ..ops.ec_tpu import gf_bitmatmul

        k, m, s = self.k, self.m, self.shard_bytes
        enc_bitmat = jnp.asarray(self._enc_bitmat_np, dtype=jnp.bfloat16)
        hash_fn = blake3_batch_fn(s)

        def fwd(data, nvalid=None):
            b = data.shape[0]
            parity = gf_bitmatmul(enc_bitmat, data)
            shards = jnp.concatenate([data, parity], axis=1)  # (B, k+m, S)
            hashes = hash_fn(shards.reshape(b * (k + m), s)).reshape(b, k + m, 32)
            # scrub stats: block count + exact xor-fold of all hash words
            # (a corruption-sensitive fleet summary).  XOR is realized as
            # per-bit add-reduce mod 2 — GSPMD supports add all-reduce on
            # every backend, unlike a bitwise-xor reduction.
            hw = hashes.reshape(b, (k + m) * 8, 4).astype(jnp.uint32)
            hwords = hw[..., 0] | (hw[..., 1] << 8) | (hw[..., 2] << 16) | (hw[..., 3] << 24)
            bitpos = jnp.arange(32, dtype=jnp.uint32)
            hbits = ((hwords[..., None] >> bitpos) & 1).astype(jnp.int32)  # (B,W,32)
            if nvalid is None:
                count = jnp.uint32(b)
            else:
                valid = (jnp.arange(b) < nvalid).astype(jnp.int32)  # (B,)
                hbits = hbits * valid[:, None, None]
                count = nvalid.astype(jnp.uint32)
            parities = hbits.sum(axis=(0, 1)) & 1  # (32,)
            fold = (parities.astype(jnp.uint32) << bitpos).sum(dtype=jnp.uint32)
            stats = jnp.stack([count, fold])
            return parity, hashes, stats

        return fwd

    def jitted(self):
        import jax

        if "jit" not in self._fns:
            self._fns["jit"] = jax.jit(self.encode_and_hash_fn())
        return self._fns["jit"]

    def example_batch(self, batch: int = 4, seed: int = 0) -> np.ndarray:
        rng = np.random.default_rng(seed)
        return rng.integers(
            0, 256, (batch, self.k, self.shard_bytes), dtype=np.uint8
        )

    # --- multi-chip step ----------------------------------------------------

    def sharded_step(self, mesh):
        """The full multi-chip repair/scrub step jitted over `mesh`:
        block-batch sharded over the "blocks" axis, coding matrices
        replicated, scrub stats psum-reduced across the mesh.

        The step takes (data, nvalid): explicit shardings require the batch
        to divide the mesh, so uneven batches arrive zero-padded (see
        `sharded_apply`) and `nvalid` masks the pad blocks out of the scrub
        statistics (their parity/hash rows are sliced off host-side)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        fwd = self.encode_and_hash_fn()
        data_sharding = NamedSharding(mesh, P("blocks"))
        out_shardings = (
            NamedSharding(mesh, P("blocks")),
            NamedSharding(mesh, P("blocks")),
            NamedSharding(mesh, P()),
        )

        return jax.jit(
            fwd,
            in_shardings=(data_sharding, NamedSharding(mesh, P())),
            out_shardings=out_shardings,
        )

    def sharded_apply(self, mesh, data: np.ndarray):
        """Host entry for the mesh step with ANY batch size: zero-pads the
        block batch to its power-of-two bucket and up to a multiple of
        the mesh (ops/bucketing.py — one compiled step per bucket class,
        not one per caller batch size), runs the sharded step, slices
        the pad rows back off.  Returns (parity, hashes, stats) as
        numpy, stats covering only the real blocks.  SYNCHRONOUS (the
        block_until_ready is a device round-trip): async callers must
        dispatch via asyncio.to_thread (lint rule `host-sync`)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.bucketing import pad_for_mesh

        # keyed by the Mesh itself (hashable): an id() key could collide
        # when a GC'd mesh's id is reused, returning a compiled step bound
        # to dead devices
        key = ("sharded", mesh)
        if key not in self._fns:
            self._fns[key] = self.sharded_step(mesh)
        step = self._fns[key]

        n = mesh.devices.size
        b = data.shape[0]
        data = pad_for_mesh(data, n)
        data_dev = jax.device_put(
            jnp.asarray(data), NamedSharding(mesh, P("blocks"))
        )
        parity, hashes, stats = jax.block_until_ready(
            step(data_dev, jnp.uint32(b))
        )
        return (
            np.asarray(parity)[:b],
            np.asarray(hashes)[:b],
            np.asarray(stats),
        )
