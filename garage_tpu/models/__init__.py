from .pipeline import ScrubRepairPipeline

__all__ = ["ScrubRepairPipeline"]
