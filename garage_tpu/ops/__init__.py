"""TPU compute kernels (JAX/XLA) and their CPU reference implementations.

This layer is pure array math — no storage or RPC types.  The block store's
`BlockCodec` (garage_tpu/block/codec/) is the seam that feeds it.

  gf.py       GF(2^8) arithmetic, Cauchy Reed-Solomon matrices, bit-matrix
              expansion, and a vectorized numpy reference codec (the oracle
              every TPU kernel is checked bit-for-bit against).
  ec_tpu.py   The TPU codec: erasure encode/reconstruct as int8 bit-plane
              matmuls on the MXU, batched over thousands of blocks per
              dispatch.
  blake3_ref.py  Pure-Python BLAKE3 (oracle).
  hash_tpu.py    Batched BLAKE3 over blocks in JAX (scrub offload).
"""
