"""TPU offload telemetry: every device dispatch leaves a metrics trail.

The driver-gating throughput metric failed silently for five rounds
partly because the offload path exported nothing — no dispatch counts,
no batch sizes, no platform — so a wedge or a silent CPU fallback looked
identical to healthy traffic until a human read a JSON artifact.  This
module is the shared recorder the EC codec (ops/ec_tpu.py), the batched
hasher (ops/hash_tpu.py) and the block codec layer (block/codec/) call
around each device dispatch.  Families (rendered by the admin /metrics
endpoint via utils/metrics.py; catalogued in doc/monitoring.md):

  tpu_codec_dispatch_total{kernel,platform}      dispatches
  tpu_codec_bytes_total{kernel,platform}         payload bytes processed
  tpu_codec_batch_size{kernel}                   blocks/dispatch histogram
  tpu_codec_dispatch_duration{kernel,platform}   seconds histogram
  jax_backend_platform{platform}                 1 for each backend that
                                                 has actually served a
                                                 dispatch (scrape-time) —
                                                 a bench believing it ran
                                                 on TPU while the gauge
                                                 says {platform="cpu"} is
                                                 the five-round bug class
                                                 this plane exists for
  tpu_mesh_engaged_total{kernel,platform,devices}  dispatches actually
                                                 served by the multi-
                                                 device shard_map mesh
                                                 (vs falling back to a
                                                 single device) — the
                                                 repair planner's batch
                                                 coalescing exists to
                                                 make this advance
"""

from __future__ import annotations

from contextlib import contextmanager

from ..utils.metrics import SIZE_BUCKETS, registry

registry.set_buckets("tpu_codec_batch_size", SIZE_BUCKETS)

_platforms_seen: set[str] = set()


def resolved_platform(pin: str | None = None) -> str:
    """The platform label for a dispatch: the pinned platform if the
    caller has one, else jax's resolved default backend, else "unknown"
    (telemetry must never fail the math it observes)."""
    if pin:
        return pin
    try:
        import jax

        return jax.default_backend()
    # graft-lint: allow-swallow(best-effort backend probe; "unknown" is a valid answer)
    except Exception:  # noqa: BLE001
        return "unknown"


def is_host_platform(platform: str | None) -> bool:
    """THE definition of "this dispatch would run on the host" — the
    one backend-string comparison the codec surface is allowed (and
    lint-forced, rule `backend-gate`) to route through.  Scattered
    `plat == "cpu"` checks are how PR 4's silent single-device fallback
    stayed invisible; a shared gate keeps every fallback decision
    consistent and greppable.  Unresolved/unknown platforms count as
    host: never prefer the device path on a backend we could not even
    name."""
    return platform is None or platform in ("cpu", "unknown", "")


def platforms_seen() -> list[str]:
    """Backends that have actually served a dispatch in this process
    (the label set behind the `jax_backend_platform` gauge) — consumed
    by the cluster telemetry digest (rpc/telemetry_digest.py)."""
    return sorted(_platforms_seen)


def note_platform(platform: str) -> None:
    """Register the scrape-time backend gauge once per resolved platform
    (labels are fixed at registration, so the platform must already be
    resolved — which it is by the time any dispatch runs)."""
    if platform in _platforms_seen:
        return
    _platforms_seen.add(platform)
    registry.register_gauge(
        "jax_backend_platform", (("platform", platform),), lambda: 1.0
    )


def mesh_engaged(kernel: str, platform: str, devices: int) -> None:
    """Count one dispatch that actually ran on the multi-device mesh
    path.  Recorded by EcTpu AFTER the mesh call returns (a mesh attempt
    that fell back to single-device must not count — the whole point is
    distinguishing the two)."""
    registry.incr(
        "tpu_mesh_engaged_total",
        (
            ("kernel", kernel),
            ("platform", platform),
            ("devices", str(devices)),
        ),
    )


@contextmanager
def dispatch(kernel: str, platform: str, batch: int, nbytes: int):
    """Instrument one device dispatch: counters + batch-size histogram on
    entry, duration histogram (and `_errors` counter, via the registry
    timer) around the body."""
    lbl = (("kernel", kernel), ("platform", platform))
    registry.incr("tpu_codec_dispatch_total", lbl)
    registry.incr("tpu_codec_bytes_total", lbl, nbytes)
    registry.observe("tpu_codec_batch_size", (("kernel", kernel),), float(batch))
    note_platform(platform)
    with registry.timer("tpu_codec_dispatch_duration", lbl):
        yield
