"""TPU offload telemetry: every device dispatch leaves a metrics trail.

The driver-gating throughput metric failed silently for five rounds
partly because the offload path exported nothing — no dispatch counts,
no batch sizes, no platform — so a wedge or a silent CPU fallback looked
identical to healthy traffic until a human read a JSON artifact.  This
module is the shared recorder the EC codec (ops/ec_tpu.py), the batched
hasher (ops/hash_tpu.py) and the block codec layer (block/codec/) call
around each device dispatch.  Families (rendered by the admin /metrics
endpoint via utils/metrics.py; catalogued in doc/monitoring.md):

  tpu_codec_dispatch_total{kernel,platform}      dispatches
  tpu_codec_bytes_total{kernel,platform}         payload bytes processed
  tpu_codec_batch_size{kernel}                   blocks/dispatch histogram
  tpu_codec_dispatch_duration{kernel,platform}   seconds histogram
  jax_backend_platform{platform}                 1 for each backend that
                                                 has actually served a
                                                 dispatch (scrape-time) —
                                                 a bench believing it ran
                                                 on TPU while the gauge
                                                 says {platform="cpu"} is
                                                 the five-round bug class
                                                 this plane exists for
  tpu_mesh_engaged_total{kernel,platform,devices}  dispatches actually
                                                 served by the multi-
                                                 device shard_map mesh
                                                 (vs falling back to a
                                                 single device) — the
                                                 repair planner's batch
                                                 coalescing exists to
                                                 make this advance

Codec X-ray families (ISSUE 17 — the instrument ROADMAP item 1's
pjit/AOT/double-buffering rewrite aims with; catalogued in
doc/monitoring.md §"Codec X-ray"):

  tpu_codec_pad_requested_total{kernel}   batch rows callers asked for
  tpu_codec_pad_padded_total{kernel}      batch rows actually dispatched
                                          (after pow2 bucketing) — the
                                          cumulative quotient is the
                                          pad-waste fraction
  tpu_codec_pad_waste{kernel}             cumulative pad-waste gauge,
                                          1 - requested/padded
  tpu_codec_transfer_duration{kernel}     host<->device marshalling secs
                                          per dispatch (pad + fetch) (H)
  tpu_codec_compute_duration{kernel}      on-device compute secs (H)
  tpu_codec_overlap_efficiency{kernel}    EWMA of wall / (transfer +
                                          compute) per dispatch — 1.0 =
                                          strictly sequential phases
                                          (today's truth); the
                                          double-buffering rewrite must
                                          push this DOWN, exactly like
                                          PR 6's api_s3_overlap_efficiency
                                          for the PUT pipeline
  tpu_compile_duration{cache}             compile-event wall seconds (H):
                                          one observation per
                                          instrumented-cache miss AND per
                                          first dispatch of a cold
                                          (kernel, bucket) shape class —
                                          count = compile events, sum =
                                          total seconds lost to lowering
"""

from __future__ import annotations

import time
from contextlib import contextmanager

from ..utils.metrics import SIZE_BUCKETS, registry

registry.set_buckets("tpu_codec_batch_size", SIZE_BUCKETS)

_platforms_seen: set[str] = set()

# (kernel, padded-bucket) shape classes that have dispatched at least
# once in this process: the first dispatch of a class pays XLA lowering
# inside its wall time, so it is recorded as a compile event; repeats
# are executable-cache hits and record nothing
_shape_seen: set[tuple[str, int]] = set()

# per-kernel overlap-efficiency EWMA state (same alpha as the latency
# X-ray's PhaseAggregator, so the two gauges read on the same scale)
EWMA_ALPHA = 0.2
_overlap_ewma: dict[str, float] = {}


def resolved_platform(pin: str | None = None) -> str:
    """The platform label for a dispatch: the pinned platform if the
    caller has one, else jax's resolved default backend, else "unknown"
    (telemetry must never fail the math it observes)."""
    if pin:
        return pin
    try:
        import jax

        return jax.default_backend()
    # graft-lint: allow-swallow(best-effort backend probe; "unknown" is a valid answer)
    except Exception:  # noqa: BLE001
        return "unknown"


def is_host_platform(platform: str | None) -> bool:
    """THE definition of "this dispatch would run on the host" — the
    one backend-string comparison the codec surface is allowed (and
    lint-forced, rule `backend-gate`) to route through.  Scattered
    `plat == "cpu"` checks are how PR 4's silent single-device fallback
    stayed invisible; a shared gate keeps every fallback decision
    consistent and greppable.  Unresolved/unknown platforms count as
    host: never prefer the device path on a backend we could not even
    name."""
    return platform is None or platform in ("cpu", "unknown", "")


def platforms_seen() -> list[str]:
    """Backends that have actually served a dispatch in this process
    (the label set behind the `jax_backend_platform` gauge) — consumed
    by the cluster telemetry digest (rpc/telemetry_digest.py)."""
    return sorted(_platforms_seen)


def note_platform(platform: str) -> None:
    """Register the scrape-time backend gauge once per resolved platform
    (labels are fixed at registration, so the platform must already be
    resolved — which it is by the time any dispatch runs)."""
    if platform in _platforms_seen:
        return
    _platforms_seen.add(platform)
    registry.register_gauge(
        "jax_backend_platform", (("platform", platform),), lambda: 1.0
    )


def mesh_engaged(kernel: str, platform: str, devices: int) -> None:
    """Count one dispatch that actually ran on the multi-device mesh
    path.  Recorded by EcTpu AFTER the mesh call returns (a mesh attempt
    that fell back to single-device must not count — the whole point is
    distinguishing the two)."""
    registry.incr(
        "tpu_mesh_engaged_total",
        (
            ("kernel", kernel),
            ("platform", platform),
            ("devices", str(devices)),
        ),
    )


def compile_event(cache: str, secs: float) -> None:
    """Record one compile event (wall seconds lost to lowering) for a
    cache/kernel family.  Two producers feed this histogram: the
    instrumented-cache miss path (utils/compile_cache.py — jit/trace
    construction) and the first dispatch of a cold (kernel, bucket)
    shape class (DispatchRecord._finish — the XLA lowering a fresh
    shape pays inside its first wall time)."""
    registry.observe("tpu_compile_duration", (("cache", cache),), secs)


def record_pad(kernel: str, requested: int, padded: int) -> None:
    """Account one dispatch's bucket padding: `requested` batch rows
    asked for, `padded` rows actually shipped.  The cumulative quotient
    is the per-kernel pad-waste fraction (gauge `tpu_codec_pad_waste`),
    bounded at 0.5 by pow2 bucketing — a value above that means a pad
    path stopped routing through ops/bucketing.py."""
    lbl = (("kernel", kernel),)
    registry.incr("tpu_codec_pad_requested_total", lbl, float(requested))
    registry.incr("tpu_codec_pad_padded_total", lbl, float(max(padded, requested)))
    req = registry.counters[("tpu_codec_pad_requested_total", lbl)]
    pad = registry.counters[("tpu_codec_pad_padded_total", lbl)]
    if pad > 0:
        registry.set_gauge(
            "tpu_codec_pad_waste", lbl, round(1.0 - req / pad, 4)
        )


class DispatchRecord:
    """Per-dispatch X-ray handle yielded by `dispatch()`: the call site
    reports its pad geometry and brackets its transfer/compute phases;
    the exit path turns those into pad-waste counters, the per-kernel
    overlap-efficiency EWMA, and first-dispatch compile events."""

    __slots__ = ("kernel", "platform", "requested", "padded",
                 "transfer_secs", "compute_secs")

    def __init__(self, kernel: str, platform: str):
        self.kernel = kernel
        self.platform = platform
        self.requested: int | None = None
        self.padded: int | None = None
        self.transfer_secs = 0.0
        self.compute_secs = 0.0

    def pad(self, requested: int, padded: int) -> None:
        """Report this dispatch's batch geometry (first call wins: a
        mesh attempt that fell back must not double-count its pad)."""
        if self.requested is not None:
            return
        self.requested, self.padded = int(requested), int(padded)
        record_pad(self.kernel, requested, padded)

    @contextmanager
    def transfer(self):
        """Bracket host<->device marshalling (pad copy, device_put, the
        blocking fetch back to numpy)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.transfer_secs += dt
            registry.observe(
                "tpu_codec_transfer_duration", (("kernel", self.kernel),), dt
            )

    @contextmanager
    def compute(self):
        """Bracket the device call itself (enqueue on async backends —
        the fetch in `transfer()` absorbs the wait, which is exactly the
        sequential-phases truth the overlap gauge reports)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.compute_secs += dt
            registry.observe(
                "tpu_codec_compute_duration", (("kernel", self.kernel),), dt
            )

    def _finish(self, wall: float) -> None:
        # first dispatch of a cold (kernel, bucket) shape class pays XLA
        # lowering inside `wall`; repeats are executable-cache hits and
        # record no compile time (asserted by tests/test_codec_xray.py).
        # The native "host" paths have no lowering step at all, so they
        # never produce shape-class compile events.
        if self.padded is not None and self.platform != "host":
            key = (self.kernel, self.padded)
            if key not in _shape_seen:
                _shape_seen.add(key)
                compile_event(self.kernel, wall)
        phases = self.transfer_secs + self.compute_secs
        if phases > 0 and wall > 0:
            eff = wall / phases
            prev = _overlap_ewma.get(self.kernel)
            ewma = eff if prev is None else (
                EWMA_ALPHA * eff + (1 - EWMA_ALPHA) * prev
            )
            _overlap_ewma[self.kernel] = ewma
            registry.set_gauge(
                "tpu_codec_overlap_efficiency",
                (("kernel", self.kernel),), round(ewma, 4),
            )


@contextmanager
def dispatch(kernel: str, platform: str, batch: int, nbytes: int):
    """Instrument one device dispatch: counters + batch-size histogram on
    entry, duration histogram (and `_errors` counter, matching the
    registry-timer contract) around the body.  Yields a DispatchRecord
    the call site MAY feed pad geometry and transfer/compute phases —
    plain `with dispatch(...):` callers keep working unchanged."""
    lbl = (("kernel", kernel), ("platform", platform))
    registry.incr("tpu_codec_dispatch_total", lbl)
    registry.incr("tpu_codec_bytes_total", lbl, nbytes)
    registry.observe("tpu_codec_batch_size", (("kernel", kernel),), float(batch))
    note_platform(platform)
    rec = DispatchRecord(kernel, platform)
    t0 = time.perf_counter()
    try:
        yield rec
    except BaseException:
        registry.observe(
            "tpu_codec_dispatch_duration", lbl, time.perf_counter() - t0
        )
        registry.incr("tpu_codec_dispatch_duration_errors", lbl)
        raise
    wall = time.perf_counter() - t0
    registry.observe("tpu_codec_dispatch_duration", lbl, wall)
    rec._finish(wall)


def reset_xray_state() -> None:
    """Drop the process-wide shape-class and EWMA state (tests that
    assert cold-class compile accounting need a cold process view)."""
    _shape_seen.clear()
    _overlap_ewma.clear()


def _finite_quantile(q: float | None) -> float | None:
    """Histogram quantiles above the top bucket come back as +Inf, which
    is not JSON-able; clamp to 2x the largest latency bucket bound so
    the snapshot stays serializable while still reading as 'way over'."""
    if q is None:
        return None
    return min(q, 16.384)


def codec_snapshot(r=None) -> dict:
    """One JSON-able view of the codec X-ray, computed from a metrics
    registry (default: the process registry).  The SINGLE source the
    digest `codec.*` keys, `GET /v1/codec`, the admin-RPC `codec` op and
    bench.py's `detail.codec` all read, so the same numbers appear on
    every surface (the acceptance bar for ISSUE 17)."""
    r = r or registry
    req = r.counter_family_sum("tpu_codec_pad_requested_total")
    pad = r.counter_family_sum("tpu_codec_pad_padded_total")
    cm = r.family_merge("tpu_compile_duration")
    ll99 = _finite_quantile(
        r.family_quantile("block_codec_batch_lane_linger", 0.99)
    )
    kernels: dict[str, dict] = {}
    for (name, labels), v in sorted(r.counters.items()):
        if name not in (
            "tpu_codec_pad_requested_total", "tpu_codec_pad_padded_total"
        ):
            continue
        kern = dict(labels).get("kernel", "")
        k = kernels.setdefault(
            kern, {"requested": 0, "padded": 0, "padWaste": 0.0,
                   "overlapEfficiency": None},
        )
        field = "requested" if name.endswith("requested_total") else "padded"
        k[field] += int(v)
    ovls = []
    for kern, k in kernels.items():
        if k["padded"]:
            k["padWaste"] = round(1.0 - k["requested"] / k["padded"], 4)
        g = r.gauges.get(
            ("tpu_codec_overlap_efficiency", (("kernel", kern),))
        )
        if g is not None:
            k["overlapEfficiency"] = round(g, 4)
            ovls.append(g)
    compile_by_cache: dict[str, dict] = {}
    for (name, labels), (cnt, total, _b) in sorted(r.durations.items()):
        if name != "tpu_compile_duration":
            continue
        cache = dict(labels).get("cache", "")
        compile_by_cache[cache] = {
            "events": int(cnt), "secs": round(total, 6),
        }
    lanes: dict[str, dict] = {}
    for (name, labels), (cnt, total, _b) in sorted(r.durations.items()):
        if name != "block_codec_batch_lane_linger":
            continue
        ld = dict(labels)
        lane = lanes.setdefault(ld.get("lane", ""), {"flush": {}})
        p99 = _finite_quantile(r.quantile(name, labels, 0.99))
        lane["flush"][ld.get("flush", "")] = {
            "blocks": int(cnt),
            "lingerSecsTotal": round(total, 6),
            "lingerP99": round(p99, 6) if p99 is not None else None,
        }
    return {
        "dispatches": int(r.counter_family_sum("tpu_codec_dispatch_total")),
        "padWaste": round(1.0 - req / pad, 4) if pad else 0.0,
        "compileEvents": int(cm[0]) if cm else 0,
        "compileSecs": round(cm[1], 6) if cm else 0.0,
        "overlapEfficiency": (
            round(sum(ovls) / len(ovls), 4) if ovls else 0.0
        ),
        "laneLingerP99": round(ll99, 6) if ll99 is not None else 0.0,
        "platforms": platforms_seen(),
        "kernels": kernels,
        "compile": compile_by_cache,
        "lanes": lanes,
    }


# newest probe profile, parsed once per (path, mtime) — probes are
# banked by bench runs, not by the daemon, so this ~never invalidates
_probe_cache: dict = {}


def probe_failure_summary(root: str | None = None) -> dict | None:
    """Newest banked TPU probe wedge profile (bench.py phased_probe,
    ISSUE 11: `tpu_runs/probe_profile_*.json`), reduced to the verdict
    line `garage stats` / `cluster top` print: the structured
    failure_reason — which phase stuck, rc, timeout, stderr evidence
    length — instead of "wedged at devices" folklore.  None when no
    profile is banked (CPU dev boxes, or a probe that has only ever
    succeeded — success banks no profile)."""
    import glob
    import json
    import os

    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    paths = sorted(
        glob.glob(os.path.join(root, "tpu_runs", "probe_profile_*.json"))
    )
    if not paths:
        return None
    path = paths[-1]
    try:
        key = (path, os.path.getmtime(path))
        if _probe_cache.get("key") == key:
            return _probe_cache["summary"]
        # graft-lint: allow-blocking(one small banked JSON artifact, read once per (path, mtime) then served from cache)
        with open(path) as f:
            prof = json.load(f)
    except (OSError, ValueError):
        return None
    fr = prof.get("failure_reason")
    if not fr:
        # pre-ISSUE-11 profile: derive the reason the way phased_probe
        # now does — the bracket child that targeted the wedged phase
        # carries the stderr evidence, the full run is the fallback
        wedged = prof.get("wedged_at")
        culprit = next(
            (
                b
                for b in prof.get("brackets", [])
                if b.get("phase_arg") == wedged
            ),
            prof.get("full") or {},
        )
        fr = {
            "phase": wedged,
            "rc": culprit.get("rc"),
            "timed_out": culprit.get("rc") == "TIMEOUT",
            "dt": culprit.get("dt"),
            "stderr_tail": culprit.get("stderr_tail", ""),
        }
    summary = {
        "result": prof.get("result")
        or ("wedged" if prof.get("wedged_at") else "failed"),
        "wedgedAt": prof.get("wedged_at"),
        "phase": fr.get("phase"),
        "rc": fr.get("rc"),
        "timedOut": bool(fr.get("timed_out")),
        "dt": fr.get("dt"),
        "stderrTail": (fr.get("stderr_tail") or "")[-400:],
        "utc": prof.get("utc"),
        "profile": os.path.basename(path),
    }
    _probe_cache["key"], _probe_cache["summary"] = key, summary
    return summary
