"""Batch-axis shape bucketing — the fixed-shape dispatch discipline.

XLA compiles one executable per input shape.  Every foreground/repair
surface that batches ragged work (the codec batcher's linger window,
the repair planner's urgency-coalesced rounds, scrub's equal-length
hash groups) therefore pads its batch axis through THESE helpers before
dispatching, so the compile cache stays bounded at log2(max_batch)
entries per shard shape instead of growing with every distinct
concurrency level the node ever sees.  Pad blocks are zeros and their
outputs are sliced off host-side (GF coding and BLAKE3 treat batch rows
independently — nothing leaks between tenants).

graft-lint's `recompile-hazard` family recognizes these helpers by name
(`bucket_batch` / `pad_to_bucket` / `pad_to_multiple`): a compiled
dispatch whose arguments never flowed through one is flagged as an
unbucketed dispatch.  Keep new pad paths routed through here — an
inline ``np.concatenate`` pad is invisible to the lint.
"""

from __future__ import annotations

import numpy as np


def bucket_batch(b: int) -> int:
    """Round a block-batch size up to its power-of-two shape class.

    The foreground codec batcher coalesces RAGGED batches (whatever
    arrived during the linger window), and XLA compiles one executable
    per input shape: unbucketed batch sizes would compile a fresh kernel
    for every distinct concurrency level the node ever sees.  Padding
    the batch axis to a power of two bounds the compile cache at
    log2(max_batch) entries per shard shape."""
    if b <= 1:
        return 1
    return 1 << (b - 1).bit_length()


def pad_to_bucket(x: np.ndarray, b_padded: int) -> np.ndarray:
    """Zero-pad the leading (batch) axis up to `b_padded` rows.  The
    caller slices the corresponding output rows back off."""
    if x.shape[0] == b_padded:
        return x
    return np.concatenate(
        [x, np.zeros((b_padded - x.shape[0], *x.shape[1:]), np.uint8)]
    )


def pad_to_multiple(x: np.ndarray, n: int) -> np.ndarray:
    """Zero-pad the leading axis up to a multiple of `n` (mesh width):
    explicit shardings require the batch to divide the device count."""
    pad = (-x.shape[0]) % n
    if not pad:
        return x
    return np.concatenate(
        [np.asarray(x), np.zeros((pad, *x.shape[1:]), np.uint8)]
    )


def pad_for_mesh(x: np.ndarray, n: int) -> np.ndarray:
    """The mesh-dispatch pad, in one place for both mesh paths
    (`EcTpu._apply_mesh`, `ScrubRepairPipeline.sharded_apply`):
    power-of-two bucket first (bounded compile cache — one executable
    per bucket class, not one per planner round size), then up to a
    multiple of the n-device mesh."""
    return pad_to_multiple(pad_to_bucket(x, bucket_batch(x.shape[0])), n)
