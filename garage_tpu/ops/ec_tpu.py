"""TPU erasure codec: GF(2^8) coding as bit-plane matmuls on the MXU.

Design (TPU-first, no reference analog — the reference's replication has no
erasure coding; this implements the BASELINE.json north star):

GF(2^8) multiplication by a constant is GF(2)-linear on the operand's bits
(gf.gf_const_bitmatrix), so a full (r x q) GF coding matrix expands to an
(8r x 8q) 0/1 matrix B, and coding becomes

    out_bits[b, i, s] = ( B @ in_bits )[b, i, s]  mod 2

i.e. ONE dense matmul over the bit-unpacked shards, batched over blocks —
exactly the shape the MXU wants.  XOR becomes addition because we only
need the low bit of the integer accumulation.

Two data paths share that math:

1. `gf_bitmatmul` — pure-XLA einsum.  Portable (CPU/TPU), but XLA
   materializes the bit-unpacked operand in HBM: bf16 bit-planes are a 16x
   traffic blowup over the uint8 shards, capping throughput far below the
   HBM roofline.  Kept as the fallback and the CPU path.

2. `gf_bitmatmul_pallas` — fused Pallas kernel: each grid step DMAs a
   (q, TS) uint8 shard tile into VMEM, unpacks to bit-planes *in VMEM*,
   runs the (8r x 8q) @ (8q x TS) product on the MXU (int8 x int8 -> int32
   — 2x MXU rate on v5e — or bf16), takes the low bit, and re-packs bits
   to bytes with a second tiny matmul, so HBM sees only the uint8 shards
   in and the uint8 parity out (1 + r/q of input bytes — the roofline).
   Bit-packing via matmul keeps every intermediate 2-D (Mosaic-friendly):
   pack matrix P[i, 8i+t] = 2^t, with t=7 encoded as int8 -128 and
   recovered by the wrapping int32 -> uint8 cast.

The coding matrix is a traced argument: encode, decode and every repair
erasure-pattern reuse ONE compiled kernel per data shape, so batched
resync (10k blocks / dispatch) never recompiles.  Checked bit-for-bit
against the numpy LUT reference in tests/test_ec.py.
"""

from __future__ import annotations

import logging

import numpy as np

from ..utils.compile_cache import instrumented_cache, record_cache_event
from . import gf, telemetry
from .bucketing import bucket_batch, pad_for_mesh, pad_to_bucket

__all__ = [
    "bucket_batch", "pad_for_mesh", "pad_to_bucket",  # re-exported
    "gf_bitmatmul", "gf_bitmatmul_pallas", "ec_apply_fn",
    "ec_apply_fn_mesh", "ec_encode_hash_fn", "blake3_supported_len",
    "EcTpu",
]


def _jax():
    import jax  # deferred so CPU-only code paths never pay the import

    return jax


def gf_bitmatmul(bitmat, x):
    """Pure-XLA bit-plane coding body (portable fallback).

    bitmat: (8r, 8q) 0/1 bf16;  x: (B, q, S) uint8  ->  (B, r, S) uint8.
    """
    import jax.numpy as jnp

    b, q, s = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, :, None, :] >> shifts[None, None, :, None]) & 1  # (B,q,8,S)
    bits = bits.reshape(b, q * 8, s).astype(jnp.bfloat16)
    acc = jnp.einsum(
        "ij,bjs->bis", bitmat, bits, preferred_element_type=jnp.float32
    )
    out_bits = acc.astype(jnp.int32) & 1  # exact: acc <= 8q < 2^24
    r = bitmat.shape[0] // 8
    out_bits = out_bits.reshape(b, r, 8, s).astype(jnp.uint8)
    weights = (jnp.uint8(1) << shifts)[None, None, :, None]
    return (out_bits * weights).sum(axis=2, dtype=jnp.uint8)


# --- fused Pallas kernel -----------------------------------------------------

def _pick_tile(s: int, cap: int | None = None) -> int:
    """Largest lane-tile (multiple of 128) dividing S, capped at `cap`
    (default 8192, overridable via GARAGE_EC_TILE for on-chip tuning:
    bigger tiles amortize per-grid-step overhead against VMEM budget)."""
    import os

    cap = cap or int(os.environ.get("GARAGE_EC_TILE", "8192"))
    for ts in (65536, 32768, 16384, 8192, 4096, 2048, 1024, 512, 256, 128):
        if ts <= cap and s % ts == 0:
            return ts
    return 0  # S not a multiple of 128: caller must use the einsum path


def _plane_major_cols(bitmat, q: int):
    """Permute (8r, 8q) standard-layout columns (8j+a) to plane-major (a*q+j)
    so the kernel can build its RHS by concatenating 8 shift-planes."""
    r8 = bitmat.shape[0]
    return bitmat.reshape(r8, q, 8).transpose(0, 2, 1).reshape(r8, 8 * q)


def _pack_matrix(r: int) -> np.ndarray:
    """(r, 8r) int8 bit-pack matrix: P[i, 8i+t] = 2^t, t=7 as -128 (two's
    complement; the wrapping int32 -> uint8 cast restores bit 7)."""
    p = np.zeros((r, 8 * r), dtype=np.int8)
    for i in range(r):
        for t in range(8):
            p[i, 8 * i + t] = -128 if t == 7 else (1 << t)
    return p


def gf_bitmatmul_pallas(bitmat, x, *, dot_dtype: str = "int8", interpret: bool = False):
    """Fused unpack -> MXU matmul -> pack kernel.

    bitmat: (8r, 8q) 0/1 integer array (standard gf.bitmatrix_of layout);
    x: (B, q, S) uint8 with S a multiple of 128  ->  (B, r, S) uint8.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, q, s = x.shape
    r8, q8 = bitmat.shape
    assert q8 == 8 * q, (bitmat.shape, x.shape)
    r = r8 // 8
    ts = _pick_tile(s)
    assert ts, f"shard size {s} not a multiple of 128; use the einsum path"

    mxu_dtype = jnp.int8 if dot_dtype == "int8" else jnp.bfloat16
    acc_dtype = jnp.int32 if dot_dtype == "int8" else jnp.float32
    w = _plane_major_cols(bitmat, q).astype(mxu_dtype)
    pack = jnp.asarray(_pack_matrix(r), dtype=jnp.int8)

    def kernel(w_ref, p_ref, x_ref, o_ref):
        xi = x_ref[0].astype(jnp.int32)  # (q, TS)
        bits = jnp.concatenate(
            [(xi >> t) & 1 for t in range(8)], axis=0
        ).astype(mxu_dtype)  # (8q, TS), plane-major rows
        acc = jax.lax.dot_general(
            w_ref[:], bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
        )  # (8r, TS)
        obits = (acc.astype(jnp.int32) & 1).astype(jnp.int8)
        packed = jax.lax.dot_general(
            p_ref[:], obits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (r, TS), values in [-128, 127]
        o_ref[0] = packed.astype(jnp.uint8)  # wrapping cast restores bit 7

    return pl.pallas_call(
        kernel,
        grid=(b, s // ts),
        in_specs=[
            pl.BlockSpec((r8, q8), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((r, r8), lambda i, j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, q, ts), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, r, ts), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, r, s), jnp.uint8),
        interpret=interpret,
    )(w, pack, x)


# --- dispatch ---------------------------------------------------------------

def _ec_body(plat: str, impl: str | None):
    """Unjitted coding body for (resolved platform, impl).  impl: None =
    auto (Pallas on TPU, einsum elsewhere)."""
    import jax.numpy as jnp

    if impl is None:
        impl = "einsum" if telemetry.is_host_platform(plat) else "pallas_int8"

    if impl == "einsum":
        def body(bitmat, x):
            return gf_bitmatmul(bitmat.astype(jnp.bfloat16), x)
    elif impl in ("pallas_int8", "pallas_bf16"):
        dd = "int8" if impl == "pallas_int8" else "bf16"
        # interpreter mode for CPU tests
        interp = telemetry.is_host_platform(plat)

        def body(bitmat, x):
            if _pick_tile(x.shape[-1]) == 0:
                return gf_bitmatmul(bitmat.astype(jnp.bfloat16), x)
            return gf_bitmatmul_pallas(bitmat, x, dot_dtype=dd, interpret=interp)
    else:
        raise ValueError(f"unknown impl {impl!r}")
    return body


def _donate_kwargs(plat: str) -> dict:
    """donate_argnums for the consume-once shard input: the fused
    foreground encode reads the data shards exactly once per dispatch,
    so on device backends the input buffer is donated to the output,
    removing a full HBM copy per dispatch (SNIPPETS pjit exemplar
    pattern).  CPU XLA cannot honor donation and warns per compile —
    skip it there.  Only the fused encode+hash path donates: the generic
    `ec_apply_fn` is also driven with long-lived device arrays
    (bench.py's timing loop) that a donation would invalidate."""
    return (
        {} if telemetry.is_host_platform(plat) else {"donate_argnums": (1,)}
    )


@instrumented_cache("ec_apply")
def ec_apply_fn(platform: str | None = None, impl: str | None = None):
    """Jitted `fn(bitmat_uint8, x_uint8) -> out_uint8`, cached per
    (platform, impl).  impl: None = auto (Pallas on TPU, einsum elsewhere),
    or one of "einsum" / "pallas_int8" / "pallas_bf16"."""
    jax = _jax()

    plat = platform or jax.default_backend()
    body = _ec_body(plat, impl)
    kwargs = {"backend": platform} if platform else {}
    return jax.jit(body, **kwargs)


@instrumented_cache("ec_apply_mesh")
def ec_apply_fn_mesh(
    platform: str | None, impl: str | None, n_devices: int, axis: str = "blocks"
):
    """(jitted_fn, mesh): the coding body shard_map-ed over an n-device 1-D
    mesh — block batch split across devices, coding matrix replicated, no
    collectives (embarrassingly parallel).  `shard_map` (not GSPMD
    auto-partitioning) because the Pallas kernel is opaque to GSPMD: each
    device runs its own pallas_call on its local batch slice.

    This is the pod-level repair fan-out path (BASELINE.md staged config
    row 5): `EcCodec.{encode,reconstruct}_batch` route here whenever >1
    device is visible, so `block/manager.bulk_reconstruct` — the real
    storage-side repair driver — scales across a v5e pod with no changes."""
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import make_mesh

    mesh = make_mesh(n_devices, axis=axis)
    plat = platform or jax.default_backend()
    body = _ec_body(plat, impl)
    # jax >= 0.5 exports shard_map at top level; 0.4.x only under
    # experimental.  Resolving both keeps the mesh path REAL on older
    # builds — an AttributeError here used to silently demote every
    # "mesh" dispatch to single-device (the fallback ate it), which is
    # exactly what tpu_mesh_engaged_total now makes visible.
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:
        from jax.experimental.shard_map import shard_map
    fn = shard_map(
        body, mesh=mesh, in_specs=(P(), P(axis)), out_specs=P(axis)
    )
    return jax.jit(fn), mesh


def blake3_supported_len(s: int) -> bool:
    """Shard lengths the batched BLAKE3 kernel accepts (ops/hash_tpu.py):
    any multiple of 64 up to one chunk, or a power-of-two number of full
    1024-byte chunks.  Shard-size classes outside this set fall back to
    host-side piece hashing."""
    if s <= 0 or s % 64:
        return False
    if s <= 1024:
        return True
    return s % 1024 == 0 and (s // 1024).bit_count() == 1


@instrumented_cache("ec_encode_hash")
def ec_encode_hash_fn(platform: str | None, impl: str | None, s: int):
    """Jitted fused foreground-encode dispatch: `fn(bitmat, x (B,k,S))
    -> (parity (B,m,S), hashes (B,k+m,32))` — the EC coding matmul AND
    the BLAKE3 of every data+parity shard in ONE device dispatch, so
    the per-piece integrity hashes (block/manager.py wrap_piece) ride
    the encode instead of costing k+m host hashes per block.  The shard
    input is donated on device backends (consume-once)."""
    jax = _jax()
    import jax.numpy as jnp

    from .hash_tpu import blake3_batch_fn

    plat = platform or jax.default_backend()
    ec_body = _ec_body(plat, impl)
    hash_fn = blake3_batch_fn(s)

    def body(bitmat, x):
        b, k, _s = x.shape
        parity = ec_body(bitmat, x)
        shards = jnp.concatenate([x, parity], axis=1)  # (B, k+m, S)
        n = shards.shape[1]
        hashes = hash_fn(shards.reshape(b * n, s)).reshape(b, n, 32)
        return parity, hashes

    kwargs = {"backend": platform} if platform else {}
    return jax.jit(body, **kwargs, **_donate_kwargs(plat))


# legacy alias used by the fused pipeline (portable einsum body)
@instrumented_cache("ec_apply_legacy")
def _apply_fn(platform: str | None):
    jax = _jax()

    kwargs = {"backend": platform} if platform else {}
    return jax.jit(gf_bitmatmul, **kwargs)


class EcTpu:
    """Batched EC(k, m) encode/reconstruct on the XLA backend.

    Host API takes/returns numpy uint8 arrays shaped (B, shards, S); the
    BlockCodec layer (garage_tpu/block/codec/ec.py) handles bytes<->array
    marshalling and dispatch batching.  Uses the fused Pallas kernel on
    TPU backends with a transparent one-time fallback to the portable
    einsum path if the Pallas lowering is unavailable.
    """

    def __init__(
        self, k: int, m: int, platform: str | None = None,
        n_devices: int | None = None,
    ):
        self.k, self.m = k, m
        self.platform = platform
        self._impl: str | None = None  # auto until first failure
        # Pod-level fan-out: shard the block batch over every visible device
        # (v5e-8 = 8-chip mesh) whenever there is more than one and the
        # batch is big enough to feed them.  n_devices pins the mesh width;
        # GARAGE_EC_MESH=0 disables (single-device dispatch).
        self._n_dev = n_devices
        self._mesh_warned = False
        self._enc_bitmat = self._to_dev(gf.bitmatrix_of(gf.cauchy_parity_matrix(k, m)))
        self._recon_cache: dict[tuple[tuple[int, ...], tuple[int, ...]], object] = {}

    def _mesh_width(self) -> int:
        import os

        if os.environ.get("GARAGE_EC_MESH", "1") == "0":
            return 1
        if self._n_dev is not None:
            return self._n_dev
        jax = _jax()
        try:
            devs = jax.devices(self.platform) if self.platform else jax.devices()
        except RuntimeError:
            return 1
        return len(devs)

    def _to_dev(self, bitmat_np: np.ndarray):
        import jax.numpy as jnp

        arr = jnp.asarray(bitmat_np, dtype=jnp.uint8)
        if self.platform:
            jax = _jax()
            arr = jax.device_put(arr, jax.devices(self.platform)[0])
        return arr

    def _apply(self, bitmat, x: np.ndarray, kernel: str) -> np.ndarray:
        with telemetry.dispatch(
            kernel, telemetry.resolved_platform(self.platform),
            x.shape[0], x.nbytes,
        ) as rec:
            return self._apply_inner(bitmat, x, kernel, rec)

    def _apply_inner(
        self, bitmat, x: np.ndarray, kernel: str = "ec",
        rec: telemetry.DispatchRecord | None = None,
    ) -> np.ndarray:
        n = self._mesh_width()
        # auto-detected meshes only engage once every device gets >=2
        # blocks; an explicitly pinned width engages as soon as padding
        # wastes less than half the mesh
        min_batch = 2 * n if self._n_dev is None else n
        if n > 1 and x.shape[0] >= min_batch:
            try:
                out = self._apply_mesh(bitmat, x, n, rec)
                telemetry.mesh_engaged(
                    kernel, telemetry.resolved_platform(self.platform), n
                )
                return out
            except Exception as e:  # noqa: BLE001 — mesh path optional
                if not self._mesh_warned:
                    self._mesh_warned = True
                    import logging

                    logging.getLogger("garage.ops.ec").warning(
                        "mesh fan-out over %d devices failed (%r); "
                        "repair batches fall back to single-device "
                        "dispatch", n, e,
                    )
        b = x.shape[0]
        bucket = bucket_batch(b)
        record_cache_event("ec_dispatch_bucket", bucket == b)
        if rec is None:
            # detached record: still counts pads/phases, but no wall is
            # attributed at exit (only `_apply` owns the dispatch timer)
            rec = telemetry.DispatchRecord(kernel, "")
        rec.pad(b, bucket)
        for impl in dict.fromkeys((self._impl, "einsum")):
            fn = ec_apply_fn(self.platform, impl)
            with rec.transfer():
                xp = pad_to_bucket(x, bucket)
            try:
                with rec.compute():
                    # graft-lint: allow-donation(ec_apply_fn also drives long-lived bench/device arrays; donation would invalidate them)
                    out_dev = fn(bitmat, xp)
                with rec.transfer():
                    out = np.asarray(out_dev)
            except Exception:
                if impl == "einsum":
                    raise
                # Pallas path unavailable on this backend: pin the
                # fallback (next loop entry) and retry on einsum.
                continue
            self._impl = impl
            return out[:b]
        raise AssertionError("unreachable: einsum attempt raises on failure")

    def _apply_mesh(
        self, bitmat, x: np.ndarray, n: int,
        rec: telemetry.DispatchRecord | None = None,
    ) -> np.ndarray:
        """Shard the block batch over the n-device mesh: the batch axis
        is padded to its power-of-two bucket AND to a multiple of n with
        zero blocks (one compiled executable per bucket instead of one
        per planner round size), then the result is sliced back."""
        jax = _jax()
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        b = x.shape[0]
        if rec is None:
            # detached record (see _apply_inner)
            rec = telemetry.DispatchRecord("ec", "")
        with rec.transfer():
            xp = pad_for_mesh(x, n)
        rec.pad(b, xp.shape[0])
        fn, mesh = ec_apply_fn_mesh(self.platform, self._impl, n)
        with rec.transfer():
            xd = jax.device_put(
                jnp.asarray(xp), NamedSharding(mesh, P("blocks"))
            )
        with rec.compute():
            # graft-lint: allow-donation(mesh fallback retries the same host batch single-device; a donated input would already be gone)
            out_dev = fn(bitmat, xd)
        with rec.transfer():
            out = np.asarray(out_dev)
        return out[:b]

    def encode(self, data: np.ndarray) -> np.ndarray:
        """(B, k, S) data shards -> (B, m, S) parity shards."""
        assert data.ndim == 3 and data.shape[1] == self.k and data.dtype == np.uint8
        return self._apply(self._enc_bitmat, data, "ec_encode")

    def encode_and_hash(
        self, data: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray | None]:
        """Foreground fused dispatch: (B, k, S) data shards ->
        (parity (B, m, S), BLAKE3 hashes (B, k+m, 32) or None).

        The batch axis is padded to its power-of-two bucket
        (`bucket_batch`) so ONE compiled executable serves every ragged
        batch the codec batcher coalesces; pad rows are sliced off.
        Hashes are None when the shard length is outside the batched
        BLAKE3 kernel's supported set, or when the fused lowering is
        unavailable — callers then hash host-side (or let the receiving
        node hash, the pre-batcher behavior)."""
        assert data.ndim == 3 and data.shape[1] == self.k and data.dtype == np.uint8
        b, _k, s = data.shape
        if not blake3_supported_len(s):
            return self.encode(data), None
        bucket = bucket_batch(b)
        record_cache_event("ec_batch_bucket", bucket == b)
        plat = telemetry.resolved_platform(self.platform)
        for impl in dict.fromkeys((self._impl, "einsum")):
            try:
                fn = ec_encode_hash_fn(self.platform, impl, s)
                with telemetry.dispatch(
                    "ec_encode_hash", plat, b, data.nbytes
                ) as rec:
                    rec.pad(b, bucket)
                    # the shard input is DONATED on device backends.  Host
                    # numpy inputs survive donation (JAX donates the
                    # transient device copy, never the host buffer), so
                    # today's retry is safe either way — the rebind inside
                    # the loop is the donation rule's retry idiom, kept
                    # honest for the day a caller hands this path a
                    # device-resident batch (ROADMAP item 2's AOT/pjit
                    # migration), where attempt 1 WOULD consume the buffer
                    with rec.transfer():
                        x = pad_to_bucket(np.asarray(data), bucket)
                    with rec.compute():
                        parity, hashes = fn(self._enc_bitmat, x)
                    with rec.transfer():
                        parity, hashes = np.asarray(parity), np.asarray(hashes)
                self._impl = impl
                return parity[:b], hashes[:b]
            except Exception as e:  # noqa: BLE001 — fused path optional
                logging.getLogger("garage.ops.ec").warning(
                    "fused encode+hash (impl=%s) failed (%r); "
                    "falling back", impl, e,
                )
        return self.encode(data), None

    def reconstruct(
        self, shards: np.ndarray, present: list[int], want: list[int]
    ) -> np.ndarray:
        """shards: (B, >=k, S) surviving shards ordered as `present`.
        Returns (B, len(want), S).  One compiled kernel serves every
        erasure pattern (the pattern only changes the small traced matrix)."""
        key = (tuple(present[: self.k]), tuple(want))
        bitmat = self._recon_cache.get(key)
        record_cache_event("ec_recon_matrix", bitmat is not None)
        if bitmat is None:
            rmat = gf.reconstruction_matrix(self.k, self.m, list(key[0]), list(want))
            bitmat = self._to_dev(gf.bitmatrix_of(rmat))
            self._recon_cache[key] = bitmat
        return self._apply(bitmat, shards[:, : self.k, :], "ec_reconstruct")

    def encode_jit(self):
        """(bitmat, fn) for building fused pipelines (bench / graft entry)."""
        return self._enc_bitmat, ec_apply_fn(self.platform, self._impl)
