"""TPU erasure codec: GF(2^8) coding as bit-plane matmuls on the MXU.

Design (TPU-first, no reference analog — the reference's replication has no
erasure coding; this implements the BASELINE.json north star):

GF(2^8) multiplication by a constant is GF(2)-linear on the operand's bits
(gf.gf_const_bitmatrix), so a full (r x q) GF coding matrix expands to an
(8r x 8q) 0/1 matrix B, and coding becomes

    out_bits[b, i, s] = ( B @ in_bits )[b, i, s]  mod 2

i.e. ONE dense matmul over the bit-unpacked shards, batched over blocks —
exactly the shape the MXU wants (a skinny (8r x 8q) x (8q x B*S) product
with an enormous inner dimension).  XOR becomes addition because we only
need the low bit of the integer accumulation.

- Operands are 0/1 in bfloat16: bf16 x bf16 -> f32 accumulation is native
  MXU; sums are <= 8q <= 2048 so f32 (and bf16 inputs) are exact.
- Unpack (uint8 -> 8 bit-planes) and pack are elementwise shifts XLA fuses
  around the matmul; `& 1` realizes the mod-2.
- The coding matrix is a traced argument: encode, decode and every repair
  erasure-pattern reuse ONE compiled kernel per data shape, so batched
  resync (10k blocks / dispatch) never recompiles.

The same kernel handles encode (B = bitmatrix of the Cauchy parity matrix)
and reconstruction (B = bitmatrix of gf.reconstruction_matrix), checked
bit-for-bit against the numpy LUT reference in tests/test_ec.py.
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf


def _jax():
    import jax  # deferred so CPU-only code paths never pay the import

    return jax


def gf_bitmatmul(bitmat, x):
    """The (traceable) bit-plane coding body — THE GF(2^8) data-path kernel.

    bitmat: (8r, 8q) 0/1 bf16;  x: (B, q, S) uint8  ->  (B, r, S) uint8.
    Shared by EcTpu and the fused scrub/repair pipeline so there is exactly
    one copy of the bit-exact kernel.
    """
    import jax.numpy as jnp

    b, q, s = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, :, None, :] >> shifts[None, None, :, None]) & 1  # (B,q,8,S)
    bits = bits.reshape(b, q * 8, s).astype(jnp.bfloat16)
    acc = jnp.einsum(
        "ij,bjs->bis", bitmat, bits, preferred_element_type=jnp.float32
    )
    out_bits = acc.astype(jnp.int32) & 1  # exact: acc <= 8q < 2^24
    r = bitmat.shape[0] // 8
    out_bits = out_bits.reshape(b, r, 8, s).astype(jnp.uint8)
    weights = (jnp.uint8(1) << shifts)[None, None, :, None]
    return (out_bits * weights).sum(axis=2, dtype=jnp.uint8)


@functools.lru_cache(maxsize=None)
def _apply_fn(platform: str | None):
    """Jitted gf_bitmatmul (cached per platform)."""
    jax = _jax()

    kwargs = {}
    if platform:
        kwargs["backend"] = platform
    return jax.jit(gf_bitmatmul, **kwargs)


class EcTpu:
    """Batched EC(k, m) encode/reconstruct on the XLA backend.

    Host API takes/returns numpy uint8 arrays shaped (B, shards, S); the
    BlockCodec layer (garage_tpu/block/codec/ec.py) handles bytes<->array
    marshalling and dispatch batching.
    """

    def __init__(self, k: int, m: int, platform: str | None = None):
        self.k, self.m = k, m
        self.platform = platform
        enc_bits = gf.bitmatrix_of(gf.cauchy_parity_matrix(k, m))
        self._enc_bitmat = self._to_dev(enc_bits)
        self._recon_cache: dict[tuple[tuple[int, ...], tuple[int, ...]], object] = {}

    def _to_dev(self, bitmat_np: np.ndarray):
        import jax.numpy as jnp

        arr = jnp.asarray(bitmat_np, dtype=jnp.bfloat16)
        if self.platform:
            jax = _jax()
            arr = jax.device_put(arr, jax.devices(self.platform)[0])
        return arr

    def encode(self, data: np.ndarray) -> np.ndarray:
        """(B, k, S) data shards -> (B, m, S) parity shards."""
        assert data.ndim == 3 and data.shape[1] == self.k and data.dtype == np.uint8
        out = _apply_fn(self.platform)(self._enc_bitmat, data)
        return np.asarray(out)

    def reconstruct(
        self, shards: np.ndarray, present: list[int], want: list[int]
    ) -> np.ndarray:
        """shards: (B, >=k, S) surviving shards ordered as `present`.
        Returns (B, len(want), S).  One compiled kernel serves every
        erasure pattern (the pattern only changes the small traced matrix)."""
        key = (tuple(present[: self.k]), tuple(want))
        bitmat = self._recon_cache.get(key)
        if bitmat is None:
            rmat = gf.reconstruction_matrix(self.k, self.m, list(key[0]), list(want))
            bitmat = self._to_dev(gf.bitmatrix_of(rmat))
            self._recon_cache[key] = bitmat
        out = _apply_fn(self.platform)(bitmat, shards[:, : self.k, :])
        return np.asarray(out)

    def encode_jit(self):
        """(bitmat, fn) for building fused pipelines (bench / graft entry)."""
        return self._enc_bitmat, _apply_fn(self.platform)
