"""Pure-Python BLAKE3 (reference/oracle for the TPU kernel in hash_tpu.py).

BLAKE3 is the rebuild's shard-integrity hash (BASELINE.json: scrub becomes
TPU-bound): all-32-bit word arithmetic and a parallel chunk tree make it the
natural TPU hash, unlike the 64-bit BLAKE2b used for content addressing
(which stays on the host — it is the block identity in the metadata tables
and is computed on the write path anyway).

Implemented from the BLAKE3 paper's specification: 1024-byte chunks, 64-byte
blocks, 7-round compression with the fixed message permutation, chunk
chaining values combined in a binary tree where each left subtree is the
largest power of two number of chunks, CHUNK_START/CHUNK_END/PARENT/ROOT
flags.  Verified against the official test vectors in tests/test_blake3.py.
"""

from __future__ import annotations

import struct

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3

BLOCK_LEN = 64
CHUNK_LEN = 1024
MASK32 = 0xFFFFFFFF


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & MASK32


def _g(state: list[int], a: int, b: int, c: int, d: int, mx: int, my: int) -> None:
    state[a] = (state[a] + state[b] + mx) & MASK32
    state[d] = _rotr(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & MASK32
    state[b] = _rotr(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b] + my) & MASK32
    state[d] = _rotr(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & MASK32
    state[b] = _rotr(state[b] ^ state[c], 7)


def compress(
    cv: tuple[int, ...],
    block_words: tuple[int, ...],
    counter: int,
    block_len: int,
    flags: int,
) -> list[int]:
    state = [
        cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
        IV[0], IV[1], IV[2], IV[3],
        counter & MASK32, (counter >> 32) & MASK32, block_len, flags,
    ]
    m = list(block_words)
    for r in range(7):
        _g(state, 0, 4, 8, 12, m[0], m[1])
        _g(state, 1, 5, 9, 13, m[2], m[3])
        _g(state, 2, 6, 10, 14, m[4], m[5])
        _g(state, 3, 7, 11, 15, m[6], m[7])
        _g(state, 0, 5, 10, 15, m[8], m[9])
        _g(state, 1, 6, 11, 12, m[10], m[11])
        _g(state, 2, 7, 8, 13, m[12], m[13])
        _g(state, 3, 4, 9, 14, m[14], m[15])
        if r < 6:
            m = [m[MSG_PERMUTATION[i]] for i in range(16)]
    return [
        state[i] ^ state[i + 8] if i < 8 else state[i] ^ cv[i - 8]
        for i in range(16)
    ]


def _words(block: bytes) -> tuple[int, ...]:
    block = block.ljust(BLOCK_LEN, b"\x00")
    return struct.unpack("<16I", block)


def _chunk_output(chunk: bytes, chunk_counter: int) -> tuple[tuple[int, ...], tuple[int, ...], int, int]:
    """Process all but the last block of a chunk; return (cv, last_block_words,
    last_block_len, base_flags) so the caller can add ROOT when applicable."""
    cv = IV
    blocks = [chunk[i : i + BLOCK_LEN] for i in range(0, max(len(chunk), 1), BLOCK_LEN)]
    for i, blk in enumerate(blocks[:-1]):
        flags = CHUNK_START if i == 0 else 0
        cv = tuple(compress(cv, _words(blk), chunk_counter, BLOCK_LEN, flags)[:8])
    last = blocks[-1]
    flags = (CHUNK_START if len(blocks) == 1 else 0) | CHUNK_END
    return cv, _words(last), len(last), flags


def _root_output_bytes(
    cv: tuple[int, ...],
    block_words: tuple[int, ...],
    counter: int,
    block_len: int,
    flags: int,
    out_len: int,
) -> bytes:
    """Extended output: re-run the final compression with incrementing
    output-block counter."""
    out = b""
    ctr = 0
    while len(out) < out_len:
        words = compress(cv, block_words, ctr, block_len, flags | ROOT)
        out += struct.pack("<16I", *words)
        ctr += 1
    return out[:out_len]


def blake3(data: bytes, out_len: int = 32) -> bytes:
    """BLAKE3 hash (default mode, no key/derive)."""
    # split into chunks
    n_chunks = max(1, (len(data) + CHUNK_LEN - 1) // CHUNK_LEN)
    chunks = [data[i * CHUNK_LEN : (i + 1) * CHUNK_LEN] for i in range(n_chunks)]

    if n_chunks == 1:
        cv, last_words, last_len, flags = _chunk_output(chunks[0], 0)
        return _root_output_bytes(cv, last_words, 0, last_len, flags, out_len)

    # chunk chaining values
    cvs: list[tuple[int, ...]] = []
    for i, c in enumerate(chunks):
        cv, last_words, last_len, flags = _chunk_output(c, i)
        cvs.append(tuple(compress(cv, last_words, i, last_len, flags)[:8]))

    # binary tree: left subtree = largest power of two < total count
    def merge(nodes: list[tuple[int, ...]]) -> tuple[tuple[int, ...], tuple[int, ...]]:
        """Reduce to the final (left_cv_words..., right...) parent block."""
        if len(nodes) == 2:
            return nodes[0], nodes[1]
        split = 1 << (len(nodes) - 1).bit_length() - 1
        parts = []
        for grp in (nodes[:split], nodes[split:]):
            if len(grp) == 1:
                parts.append(grp[0])
            else:
                l, r = merge(grp)
                parts.append(tuple(compress(IV, l + r, 0, BLOCK_LEN, PARENT)[:8]))
        return parts[0], parts[1]

    left, right = merge(cvs)
    return _root_output_bytes(IV, left + right, 0, BLOCK_LEN, PARENT, out_len)
