"""Batched BLAKE3 in JAX — the TPU scrub/integrity offload.

Hashes B equal-length inputs in one XLA dispatch.  Supported lengths: any
multiple of 64 bytes up to one chunk (<=1024), or a power-of-two number of
full 1024-byte chunks — exactly the shard sizes the EC codec produces
(shards are padded to these sizes by the block layer).  Output is bit-exact
official BLAKE3 (oracle: blake3_ref.py; vectors in tests/test_blake3.py).

Structure (all uint32, wrap-around arithmetic is native):
  - the 7-round compression runs on state rows (..., 4) with the standard
    column/diagonal vectorization (rotate rows between half-rounds);
  - a `lax.scan` chains the 16 blocks of each chunk, vmapped over B x chunks;
  - chunk CVs reduce pairwise (PARENT compressions) log2(n) times;
  - ROOT flag applied on the final compression.

Elementwise VPU work, not MXU — the win is batching thousands of shard
hashes into one dispatch next to the EC matmuls so scrub never touches the
host per block.
"""

from __future__ import annotations

import numpy as np

from ..utils.compile_cache import instrumented_cache
from . import telemetry
from .blake3_ref import CHUNK_END, CHUNK_START, IV, MSG_PERMUTATION, PARENT, ROOT
from .bucketing import bucket_batch, pad_to_bucket

BLOCK_LEN = 64
CHUNK_LEN = 1024


def _build(n_chunks: int):
    """Jitted hasher; the per-chunk block count (and the 64-byte full last
    block) are derived from the input shape at trace time."""
    last_block_len = BLOCK_LEN
    import jax
    import jax.numpy as jnp
    from jax import lax

    iv = jnp.array(IV, dtype=jnp.uint32)
    perm = jnp.array(MSG_PERMUTATION, dtype=jnp.int32)

    def rotr(x, n):
        return (x >> n) | (x << (32 - n))

    def ghalf(a, b, c, d, mx, r1, r2):
        a = a + b + mx
        d = rotr(d ^ a, r1)
        c = c + d
        b = rotr(b ^ c, r2)
        return a, b, c, d

    def compress(cv, m, counter, block_len, flags):
        # cv (..., 8), m (..., 16) -> full 16-word output (..., 16)
        ctr_lo = jnp.uint32(counter & 0xFFFFFFFF) if isinstance(counter, int) else counter.astype(jnp.uint32)
        ctr_hi = jnp.uint32(0)
        tail = jnp.stack(
            jnp.broadcast_arrays(
                ctr_lo, ctr_hi, jnp.uint32(block_len), jnp.uint32(flags)
            ),
            axis=-1,
        )
        tail = jnp.broadcast_to(tail.astype(jnp.uint32), cv.shape[:-1] + (4,))
        state = jnp.concatenate(
            [cv, jnp.broadcast_to(iv[:4], cv.shape[:-1] + (4,)), tail],
            axis=-1,
        )
        a, b, c, d = (state[..., i * 4 : (i + 1) * 4] for i in range(4))
        for r in range(7):
            mx = m[..., 0:8:2]
            my = m[..., 1:8:2]
            a, b, c, d = ghalf(a, b, c, d, mx, 16, 12)
            a, b, c, d = ghalf(a, b, c, d, my, 8, 7)
            # diagonalize
            b = jnp.roll(b, -1, axis=-1)
            c = jnp.roll(c, -2, axis=-1)
            d = jnp.roll(d, -3, axis=-1)
            mx = m[..., 8:16:2]
            my = m[..., 9:16:2]
            a, b, c, d = ghalf(a, b, c, d, mx, 16, 12)
            a, b, c, d = ghalf(a, b, c, d, my, 8, 7)
            b = jnp.roll(b, 1, axis=-1)
            c = jnp.roll(c, 2, axis=-1)
            d = jnp.roll(d, 3, axis=-1)
            if r < 6:
                m = m[..., perm]
        lo = jnp.concatenate([a, b], axis=-1) ^ jnp.concatenate([c, d], axis=-1)
        hi = jnp.concatenate([c, d], axis=-1) ^ cv
        return jnp.concatenate([lo, hi], axis=-1)

    def hash_batch(x):
        # x: (B, L) uint8
        b = x.shape[0]
        # -> little-endian uint32 words (B, n_chunks, blocks, 16)
        w = x.reshape(b, n_chunks, -1, 16, 4).astype(jnp.uint32)
        words = w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)
        n_blocks = words.shape[2]
        chunk_ctr = jnp.broadcast_to(
            jnp.arange(n_chunks, dtype=jnp.uint32)[None, :], (b, n_chunks)
        )
        single_chunk = n_chunks == 1

        def step(cv, inp):
            blk, flags, block_len = inp
            out = compress(cv, blk, chunk_ctr, block_len, flags)
            return out[..., :8], None

        flags_per_block = []
        lens_per_block = []
        for i in range(n_blocks):
            f = 0
            if i == 0:
                f |= CHUNK_START
            if i == n_blocks - 1:
                f |= CHUNK_END
                if single_chunk:
                    f |= ROOT
                lens_per_block.append(last_block_len)
            else:
                lens_per_block.append(BLOCK_LEN)
            flags_per_block.append(f)

        cv0 = jnp.broadcast_to(iv, (b, n_chunks, 8))
        blocks_seq = jnp.moveaxis(words, 2, 0)  # (n_blocks, B, n_chunks, 16)
        flags_seq = jnp.array(flags_per_block, dtype=jnp.uint32)
        lens_seq = jnp.array(lens_per_block, dtype=jnp.uint32)

        if single_chunk:
            # chain all but the last block, then one final compression whose
            # full 16-word output is the root
            cv_prev = cv0
            if n_blocks > 1:
                cv_prev, _ = lax.scan(
                    step,
                    cv0,
                    (
                        blocks_seq[:-1],
                        flags_seq[:-1, None, None],
                        lens_seq[:-1, None, None],
                    ),
                )
            out = compress(
                cv_prev,
                blocks_seq[-1],
                chunk_ctr,
                jnp.uint32(last_block_len),
                jnp.uint32(flags_per_block[-1]),
            )
            root_words = out[:, 0, :8]
        else:
            # chain all 16 blocks of every chunk, then tree-reduce the CVs
            cvs, _ = lax.scan(
                step,
                cv0,
                (blocks_seq, flags_seq[:, None, None], lens_seq[:, None, None]),
            )
            n = n_chunks
            while n > 1:
                left = cvs[:, 0:n:2, :]
                right = cvs[:, 1:n:2, :]
                m = jnp.concatenate([left, right], axis=-1)  # (B, n/2, 16)
                n //= 2
                flags = PARENT | (ROOT if n == 1 else 0)
                out = compress(
                    jnp.broadcast_to(iv, m.shape[:-1] + (8,)),
                    m,
                    jnp.uint32(0),
                    jnp.uint32(BLOCK_LEN),
                    jnp.uint32(flags),
                )
                cvs = out[..., :8]
            root_words = cvs[:, 0, :]

        # -> bytes (B, 32) little-endian
        rw = root_words  # (B, 8) uint32
        out_bytes = jnp.stack(
            [(rw >> (8 * i)) & 0xFF for i in range(4)], axis=-1
        ).astype(jnp.uint8)
        return out_bytes.reshape(b, 32)

    return jax.jit(hash_batch)


@instrumented_cache("blake3_hasher")
def _hasher_for_len(length: int):
    if length % BLOCK_LEN != 0 or length == 0:
        raise ValueError("batched blake3 requires a positive multiple of 64 bytes")
    if length <= CHUNK_LEN:
        n_chunks = 1
    else:
        if length % CHUNK_LEN != 0:
            raise ValueError("multi-chunk batched blake3 requires multiple of 1024")
        n_chunks = length // CHUNK_LEN
        if n_chunks & (n_chunks - 1):
            raise ValueError("chunk count must be a power of two")
    return _build(n_chunks)


def blake3_batch(x: np.ndarray) -> np.ndarray:
    """x: (B, L) uint8 -> (B, 32) uint8 official BLAKE3 digests.

    The batch axis is padded to its power-of-two bucket (scrub hands
    this whatever group sizes the piece inventory produced — unbucketed,
    every distinct group size would compile a fresh executable); pad
    rows hash independently and are sliced off.  SYNCHRONOUS: the
    np.asarray is a device round-trip — async callers must dispatch via
    asyncio.to_thread (lint rule `host-sync`, the scrub path does)."""
    b = x.shape[0]
    fn = _hasher_for_len(x.shape[1])
    bucket = bucket_batch(b)
    with telemetry.dispatch(
        "blake3_hash", telemetry.resolved_platform(), b, x.nbytes
    ) as rec:
        rec.pad(b, bucket)
        with rec.transfer():
            xp = pad_to_bucket(np.asarray(x), bucket)
        with rec.compute():
            # graft-lint: allow-donation(callers retain and re-read the host batch; the hasher also serves fused pipelines with long-lived inputs)
            out_dev = fn(xp)
        with rec.transfer():
            return np.asarray(out_dev)[:b]


def blake3_batch_fn(length: int):
    """The jitted device function for fused pipelines (bench / graft entry)."""
    return _hasher_for_len(length)
