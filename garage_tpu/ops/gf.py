"""GF(2^8) arithmetic and Cauchy Reed-Solomon coding — CPU reference.

The erasure-coded replication mode (`replication_mode = "ec:k:m"`,
BASELINE.json north star) splits each block into k data shards and m parity
shards over GF(2^8) with the AES-friendly polynomial x^8+x^4+x^3+x^2+1
(0x11d).  This module is the bit-exact oracle for the TPU kernel in
ec_tpu.py and the host-side fallback codec.

Key construction for the TPU path: multiplication by a constant c in
GF(2^8) is GF(2)-linear on the 8 bits of the operand, i.e. an 8x8 binary
matrix M_c with M_c[b, a] = bit b of (c * 2^a).  A full (m x k) GF coding
matrix therefore expands to an (8m x 8k) binary matrix, and erasure
encoding of bit-unpacked shards becomes an integer matmul followed by
`& 1` — which XLA tiles straight onto the MXU (see ec_tpu.py).
"""

from __future__ import annotations

import numpy as np

_POLY = 0x11D

# --- log/exp tables ---------------------------------------------------------

GF_EXP = np.zeros(512, dtype=np.uint8)
GF_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    GF_EXP[_i] = _x
    GF_LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _POLY
GF_EXP[255:510] = GF_EXP[:255]


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[GF_LOG[a] + GF_LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(GF_EXP[255 - GF_LOG[a]])


# 256x256 multiplication table: MUL[c] is the 256-entry LUT for y = c*x.
# 64 KiB, built once; the numpy reference codec is gathers through this.
_PRODUCT_LOG = GF_LOG[:, None] + GF_LOG[None, :]
GF_MUL_TABLE = GF_EXP[_PRODUCT_LOG % 255].astype(np.uint8)
GF_MUL_TABLE[0, :] = 0
GF_MUL_TABLE[:, 0] = 0


# --- matrices ---------------------------------------------------------------

def cauchy_parity_matrix(k: int, m: int) -> np.ndarray:
    """(m x k) Cauchy matrix C[i, j] = 1 / (x_i + y_j), x_i = k+i, y_j = j.

    All x_i, y_j distinct => every square submatrix of [I_k ; C] is
    invertible, which is the property erasure decoding relies on.
    """
    if k + m > 255:
        raise ValueError("k+m must be <= 255 for distinct GF(2^8) points")
    c = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            c[i, j] = gf_inv((k + i) ^ j)
    return c


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8): a (p x q) @ b (q x r) -> (p x r).

    Used for small coding matrices only (the data path uses LUT gathers or
    the TPU bit-plane kernel).
    """
    p, q = a.shape
    q2, r = b.shape
    assert q == q2
    out = np.zeros((p, r), dtype=np.uint8)
    for i in range(q):
        out ^= GF_MUL_TABLE[a[:, i][:, None], b[i, :][None, :]]
    return out


def gf_invert_matrix(a: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion of a (n x n) matrix over GF(2^8)."""
    n = a.shape[0]
    assert a.shape == (n, n)
    aug = np.concatenate([a.astype(np.uint8), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise ValueError("matrix is singular over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = GF_MUL_TABLE[inv_p, aug[col]]
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] ^= GF_MUL_TABLE[int(aug[row, col]), aug[col]]
    return aug[:, n:]


def encode_matrix(k: int, m: int) -> np.ndarray:
    """Systematic (k+m x k) generator matrix [I_k ; C]."""
    return np.concatenate([np.eye(k, dtype=np.uint8), cauchy_parity_matrix(k, m)])


def reconstruction_matrix(
    k: int, m: int, present: list[int], want: list[int]
) -> np.ndarray:
    """(len(want) x k) matrix R such that  want_shards = R @ present[:k] shards.

    `present` — indices (in [0, k+m)) of at least k surviving shards (the
    first k listed are used); `want` — indices of shards to reconstruct.
    """
    if len(present) < k:
        raise ValueError(f"need >= {k} surviving shards, have {len(present)}")
    gen = encode_matrix(k, m)
    sub = gen[np.array(present[:k])]  # (k x k), invertible by Cauchy property
    inv = gf_invert_matrix(sub)  # data = inv @ present_shards
    rows = gen[np.array(want)]  # want = rows @ data
    return gf_matmul(rows, inv)


# --- bit-matrix expansion (the TPU-kernel construction) ---------------------

def gf_const_bitmatrix(c: int) -> np.ndarray:
    """8x8 binary matrix of multiplication-by-c: out_bit[b] = sum_a M[b,a]*in_bit[a] mod 2."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for a in range(8):
        prod = gf_mul(c, 1 << a)
        for b in range(8):
            m[b, a] = (prod >> b) & 1
    return m


def bitmatrix_of(coding: np.ndarray) -> np.ndarray:
    """Expand an (r x q) GF(2^8) matrix to the (8r x 8q) binary matrix acting
    on bit-unpacked shards (LSB-first bit order)."""
    r, q = coding.shape
    out = np.zeros((8 * r, 8 * q), dtype=np.uint8)
    for i in range(r):
        for j in range(q):
            out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = gf_const_bitmatrix(
                int(coding[i, j])
            )
    return out


# --- numpy reference codec ---------------------------------------------------

def apply_matrix_ref(coding: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Reference data path: out (..., r, S) = coding (r x q) @ shards (..., q, S)
    over GF(2^8), via LUT gathers.  shards uint8; leading batch dims allowed."""
    r, q = coding.shape
    assert shards.shape[-2] == q, (coding.shape, shards.shape)
    out = np.zeros(shards.shape[:-2] + (r, shards.shape[-1]), dtype=np.uint8)
    for j in range(q):
        col = shards[..., j, :]  # (..., S)
        for i in range(r):
            c = int(coding[i, j])
            if c != 0:
                out[..., i, :] ^= GF_MUL_TABLE[c][col]
    return out


def apply_matrix(coding: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Native-accelerated coding apply: the C++ extension when available
    (2-D operands), the numpy LUT reference otherwise.  Bit-identical to
    apply_matrix_ref (cross-checked in tests)."""
    if shards.ndim == 2:
        from .. import _native

        out = _native.gf8_apply(coding, shards)
        if out is not None:
            return out
    return apply_matrix_ref(coding, shards)


def encode_blocks_ref(data: np.ndarray, k: int, m: int) -> np.ndarray:
    """(..., k, S) data shards -> (..., m, S) parity shards."""
    return apply_matrix_ref(cauchy_parity_matrix(k, m), data)


def reconstruct_blocks_ref(
    shards: np.ndarray, k: int, m: int, present: list[int], want: list[int]
) -> np.ndarray:
    """shards: (..., len(present)>=k, S) surviving shards in `present` order.
    Returns (..., len(want), S) reconstructed shards."""
    rmat = reconstruction_matrix(k, m, present, want)
    return apply_matrix_ref(rmat, shards[..., : k, :])


def split_block(block: bytes, k: int) -> np.ndarray:
    """Pad a block to k equal shards -> (k, S) uint8."""
    s = (len(block) + k - 1) // k
    buf = np.zeros(k * s, dtype=np.uint8)
    buf[: len(block)] = np.frombuffer(block, dtype=np.uint8)
    return buf.reshape(k, s)
