"""Metadata-plane benchmark (VERDICT r2 Missing #6): the reference's
metadata story is memory-mapped LMDB (src/db/lmdb_adapter.rs); ours is
pure-Python engines (sqlite, append-only log).  This prints the measured
numbers so that trade-off is quantified, not assumed.

Measures, per durable engine:
  - db-layer single-op insert/get ops/sec and batched-tx insert ops/sec
  - end-to-end S3 metadata ops/sec on a single-node daemon: PUT of
    INLINE objects (< 3072 B bodies never touch the block store, so a
    PUT is a pure metadata quorum write) and ListObjectsV2 keys/sec

Output: one JSON line, same shape as bench.py
({"metric", "value", "unit", "vs_baseline", ...detail}).  The headline
metric is end-to-end inline-PUT ops/sec on the default engine (sqlite);
vs_baseline is against META_BASELINE_OPS (no published reference number
exists for this workload — the baseline is the round-3 measurement on
this box, so the ratio guards regressions).

Usage: python bench_meta.py [--quick]
"""

import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# round-3 sqlite end-to-end inline-PUT ops/s measured on the 1-CPU bench
# box (337-499 across 150-2000 objects, converging ~370); vs_baseline =
# measured/this, so < 1.0 flags a metadata-plane regression
META_BASELINE_OPS = 330.0

N_DB_OPS = 5000
N_S3_PUTS = 600
N_LIST_KEYS = 600


def bench_db_engine(engine: str, n: int, fsync=True) -> dict:
    from garage_tpu.db import open_db

    d = tempfile.mkdtemp(prefix=f"benchmeta-{engine}-")
    try:
        db = open_db(os.path.join(d, "db"), engine=engine, fsync=fsync)
        tree = db.open_tree("bench")
        val = b"v" * 128  # typical small table entry

        t0 = time.perf_counter()
        for i in range(n):
            tree.insert(b"k%08d" % i, val)
        insert_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        for i in range(n):
            assert tree.get(b"k%08d" % i) is not None
        get_s = time.perf_counter() - t0

        def batch(tx):
            for i in range(n):
                tx.insert(tree, b"b%08d" % i, val)

        t0 = time.perf_counter()
        db.transaction(batch)
        tx_insert_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        cnt = sum(1 for _ in tree.iter_range())
        scan_s = time.perf_counter() - t0
        db.close()
        return {
            "insert_ops": round(n / insert_s),
            "get_ops": round(n / get_s),
            "tx_insert_ops": round(n / tx_insert_s),
            "scan_keys_per_s": round(cnt / scan_s),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


async def bench_s3_meta(engine: str, n_puts: int, n_list: int) -> dict:
    """Single-node daemon; inline PUTs are metadata-only writes."""
    from garage_tpu.api.s3.api_server import S3ApiServer
    from garage_tpu.api.s3.client import S3Client
    from garage_tpu.model.garage import Garage
    from garage_tpu.rpc.layout.types import NodeRole
    from garage_tpu.utils.config import config_from_dict

    d = tempfile.mkdtemp(prefix=f"benchmeta-s3-{engine}-")
    try:
        cfg = config_from_dict(
            {
                "metadata_dir": os.path.join(d, "meta"),
                "data_dir": os.path.join(d, "data"),
                "db_engine": engine,
                "replication_mode": "1",
                "rpc_bind_addr": "127.0.0.1:0",
                "rpc_secret": "ab" * 32,
                "tpu": {"enable": False},
                "s3_api": {"api_bind_addr": None},
            }
        )
        g = Garage(cfg)
        await g.start()
        lm = g.layout_manager
        lm.stage_role(g.node_id, NodeRole(zone="dc0", capacity=10**12))
        lm.apply_staged()
        g.spawn_workers()
        key = await g.helper.create_key("bench")
        key.params().allow_create_bucket.update(True)
        await g.key_table.insert(key)
        s3 = S3ApiServer(g)
        await s3.start("127.0.0.1", 0)
        port = s3.runner.addresses[0][1]
        client = S3Client(f"http://127.0.0.1:{port}", key.key_id, key.secret())
        await client.create_bucket("bench")

        body = b"m" * 512  # inline (< 3072): pure metadata write
        t0 = time.perf_counter()
        for i in range(n_puts):
            await client.put_object("bench", f"obj-{i:06d}", body)
        put_s = time.perf_counter() - t0

        # make sure the listing has n_list keys to walk
        for i in range(n_puts, n_list):
            await client.put_object("bench", f"obj-{i:06d}", body)

        t0 = time.perf_counter()
        listed = 0
        token = None
        while True:
            resp = await client.list_objects_v2(
                "bench", **({"continuation_token": token} if token else {})
            )
            listed += len(resp["keys"])
            token = resp.get("next_token")
            if not token:
                break
        list_s = time.perf_counter() - t0

        await client.close()
        await s3.stop()
        await g.stop()
        return {
            "inline_put_ops": round(n_puts / put_s),
            "list_keys_per_s": round(listed / list_s),
            "listed": listed,
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main() -> None:
    quick = "--quick" in sys.argv
    n_db = 1000 if quick else N_DB_OPS
    n_puts = 150 if quick else N_S3_PUTS
    n_list = 150 if quick else N_LIST_KEYS

    from garage_tpu import _native

    engines = ["sqlite", "log"]
    if _native.available():
        engines.append("native")
    detail = {}
    for engine in engines:
        detail[engine] = bench_db_engine(engine, n_db)
        detail[engine].update(
            asyncio.run(bench_s3_meta(engine, n_puts, n_list))
        )
    # Relaxed-durability apples-to-apples (bounded-window semantics):
    # native group commit (C++ flusher, window ~ one fdatasync) vs sqlite
    # WAL + synchronous=NORMAL (sync at checkpoints).  The reference's
    # default posture (metadata_fsync = false on LMDB) is this class.
    if "native" in engines:
        detail["native"]["group_insert_ops"] = bench_db_engine(
            "native", n_db, fsync="group"
        )["insert_ops"]
    detail["sqlite"]["normal_insert_ops"] = bench_db_engine(
        "sqlite", n_db, fsync=False
    )["insert_ops"]

    headline = detail["sqlite"]["inline_put_ops"]
    print(
        json.dumps(
            {
                "metric": "meta_inline_put",
                "value": headline,
                "unit": "ops/s",
                "vs_baseline": round(headline / META_BASELINE_OPS, 3),
                "engines": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
