#!/usr/bin/env python3
"""Headline benchmark: EC(8,3) erasure-encode throughput per chip.

Runs the flagship fused pipeline (GF(2^8) coding of 1 MiB blocks) on the
default JAX backend and prints ONE JSON line:

    {"metric": "ec83_encode_GBps", "value": N, "unit": "GB/s",
     "vs_baseline": N / 10.0, "platform": "tpu"|"cpu"|"none"}

("platform" records which backend produced the number: the chip, the CPU
fallback, or "none" for the all-backends-failed sentinel line.)

Baseline (BASELINE.md north star): >= 10 GB/s EC(8,3) encode+repair on one
v5e chip.  `vs_baseline` > 1.0 means the target is beaten.

Flags: --batch (blocks per dispatch), --iters, --hash (also compute BLAKE3
shard hashes in the same dispatch), --repair (measure reconstruction of m
lost shards instead of encode).

Wedge-proofing (round-1 failure mode: the tunneled TPU backend can wedge a
process forever, even during PJRT init, and an in-process watchdog thread
cannot unwedge it).  The parent process NEVER imports jax: it runs the
measurement in a subprocess with a hard kill.  If the default-backend child
times out or dies, it retries in a fresh subprocess with JAX_PLATFORMS=cpu
(so the wedged plugin is never even initialized) and scaled-down shapes.
The driver therefore always gets a JSON line.
"""

import argparse
import json
import os
import subprocess
import sys
import time

TPU_TIMEOUT = 360.0
CPU_TIMEOUT = 270.0


def parse_args(argv):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--m", type=int, default=3)
    ap.add_argument("--block-bytes", type=int, default=1024 * 1024)
    # Default blocks-per-dispatch is backend-dependent (resolved in
    # child_main): 2048 on an accelerator, 8 on CPU.  Measured on the v5e
    # (2026-07-29), encode rate climbs with batch as dispatch/tunnel
    # overhead amortizes — 64->21.4, 128->36.4, 256->52.1, 512->67.7,
    # 1024->79.6, 2048->86.6, 4096->91.7 GB/s; 2048 is within 6% of the
    # 4 GiB-batch rate at half the HBM footprint.  On CPU a 2 GiB batch
    # would OOM/time-out the 1-core box, hence the per-backend default.
    ap.add_argument("--batch", type=int, default=None, help="blocks per dispatch")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--hash", action="store_true", help="fuse BLAKE3 shard hashing")
    ap.add_argument("--repair", action="store_true", help="bench reconstruction")
    ap.add_argument("--impl", choices=["pallas_int8", "pallas_bf16", "einsum"],
                    default=None, help="pin the EC kernel implementation")
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.hash and args.repair:
        ap.error("--hash and --repair are mutually exclusive")
    return args


def child_main(args) -> None:
    """Measurement body — runs in a subprocess the parent can hard-kill."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from garage_tpu.models.pipeline import ScrubRepairPipeline
    from garage_tpu.ops import gf

    k, m = args.k, args.m
    shard_bytes = args.block_bytes // k
    pipe = ScrubRepairPipeline(k=k, m=m, shard_bytes=shard_bytes)

    dev = jax.devices()[0]
    if args.batch is None:
        args.batch = 8 if dev.platform == "cpu" else 2048
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (args.batch, k, shard_bytes), dtype=np.uint8)
    data_dev = jax.device_put(jnp.asarray(data), dev)
    if args.verbose:
        print(f"# backend={dev.platform} device={dev}", file=sys.stderr)

    def sync(x):
        # On the tunneled axon platform block_until_ready can return before
        # execution finishes; a 1-byte host fetch is the honest barrier.
        np.asarray(x[(0,) * (x.ndim - 1)][:1])

    if args.hash:
        fn = pipe.jitted()

        def run(x):
            p, h, s = fn(x)
            return p

        sync(run(data_dev))  # warmup/compile
    else:
        from garage_tpu.ops.ec_tpu import ec_apply_fn

        if args.repair:
            # lose the first m data shards; reconstruct from survivors
            present = list(range(m, k + m))
            mat = gf.reconstruction_matrix(k, m, present[:k], list(range(m)))
        else:
            mat = gf.cauchy_parity_matrix(k, m)
        bitmat = jax.device_put(jnp.asarray(gf.bitmatrix_of(mat), jnp.uint8), dev)

        # Try the fused Pallas kernel first; fall back to the portable
        # einsum path if the backend can't lower it.  On CPU the native
        # C++ LUT codec is the framework's real encode path (the Pallas
        # kernel only exists in interpreter mode there).
        if args.impl:
            impls = [args.impl]
        elif dev.platform == "cpu":
            impls = ["native", "einsum"]
        else:
            impls = ["pallas_int8", "pallas_bf16", "einsum"]
        run = None
        for impl in impls:
            if impl == "native":
                from garage_tpu import _native

                if _native.available():
                    def run(x, _mat=mat, _np=data):
                        for b in range(_np.shape[0]):
                            out = _native.gf8_apply(_mat, _np[b])
                        return out

                    if args.verbose:
                        print("# impl=native (C++ host codec)", file=sys.stderr)
                    break
                continue
            try:
                apply_fn = ec_apply_fn(None, impl)
                out = apply_fn(bitmat, data_dev)
                sync(out)
            except Exception as e:  # noqa: BLE001 — try next impl
                print(f"# impl {impl} failed: {type(e).__name__}: {e}",
                      file=sys.stderr)
                continue
            if args.verbose:
                print(f"# impl={impl}", file=sys.stderr)

            def run(x, _fn=apply_fn):
                return _fn(bitmat, x)

            break
        if run is None:
            raise RuntimeError("no EC impl usable on this backend")

    for _ in range(args.warmup):
        sync(run(data_dev))

    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = run(data_dev)
    sync(out)
    dt = time.perf_counter() - t0

    bytes_per_iter = args.batch * k * shard_bytes  # data bytes coded
    gbps = bytes_per_iter * args.iters / dt / 1e9
    metric = "ec%d%d_%s_GBps" % (k, m, "repair" if args.repair else "encode")
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / 10.0, 4),
                "platform": dev.platform,
            }
        )
    )


def run_child(argv, env, timeout):
    """Run the measurement subprocess; return its JSON line or None."""
    cmd = [sys.executable, os.path.abspath(__file__), "--_child", *argv]
    try:
        proc = subprocess.run(
            cmd,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=timeout,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        print("# bench child timed out (backend wedged?)", file=sys.stderr)
        return None
    sys.stderr.write(proc.stderr)
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"# bench child rc={proc.returncode}, no JSON line", file=sys.stderr)
    return None


def main() -> None:
    argv = sys.argv[1:]
    args = parse_args(argv)
    if args._child:
        child_main(args)
        return

    # Attempt 1: default backend (the real chip when the tunnel is healthy).
    result = run_child(argv, dict(os.environ), TPU_TIMEOUT)

    if result is None:
        # Attempt 2: forced CPU in a fresh process — the wedged plugin is
        # never initialized.  Scale shapes down unless the user pinned them.
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # the sitecustomize dials the TPU tunnel at interpreter startup
        # when this is set — scrub it so the CPU child can never block
        env.pop("PALLAS_AXON_POOL_IPS", None)
        cpu_argv = list(argv)
        if "--batch" not in " ".join(argv):
            cpu_argv += ["--batch", "8"]
        if "--iters" not in " ".join(argv):
            # long enough that scheduler noise on the 1-CPU box doesn't
            # dominate (5 iters = ~80 ms of work; 40 = ~1.5 s)
            cpu_argv += ["--iters", "40"]
        print("# default backend unusable; falling back to cpu", file=sys.stderr)
        result = run_child(cpu_argv, env, CPU_TIMEOUT)

    if result is None:
        # Last resort: still emit a parseable line; value 0 = failed run.
        metric = "ec%d%d_%s_GBps" % (
            args.k,
            args.m,
            "repair" if args.repair else "encode",
        )
        result = {
            "metric": metric,
            "value": 0.0,
            "unit": "GB/s",
            "vs_baseline": 0.0,
            "platform": "none",
            "error": "all backends failed or timed out",
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
