#!/usr/bin/env python3
"""Headline benchmark: EC(8,3) erasure-encode throughput per chip.

Runs the flagship fused pipeline (GF(2^8) bit-plane matmul encode of 1 MiB
blocks) on the default JAX backend and prints ONE JSON line:

    {"metric": "ec83_encode_GBps", "value": N, "unit": "GB/s",
     "vs_baseline": N / 10.0}

Baseline (BASELINE.md north star): >= 10 GB/s EC(8,3) encode+repair on one
v5e chip.  `vs_baseline` > 1.0 means the target is beaten.

Flags: --batch (blocks per dispatch), --iters, --hash (also compute BLAKE3
shard hashes in the same dispatch), --repair (measure reconstruction of m
lost shards instead of encode).
"""

import argparse
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--m", type=int, default=3)
    ap.add_argument("--block-bytes", type=int, default=1024 * 1024)
    ap.add_argument("--batch", type=int, default=64, help="blocks per dispatch")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--hash", action="store_true", help="fuse BLAKE3 shard hashing")
    ap.add_argument("--repair", action="store_true", help="bench reconstruction")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    import jax

    # Watchdog: the tunneled TPU platform can wedge (ops hang forever).
    # Probe it from a daemon thread; if the probe doesn't finish in time,
    # fall back to the CPU backend so the driver always gets a JSON line.
    import threading

    probe_ok = threading.Event()

    def _probe():
        try:
            import jax.numpy as _jnp

            np.asarray(_jnp.arange(4.0) * 2)
            probe_ok.set()
        except Exception:  # noqa: BLE001 — fall through to CPU
            pass

    backend = None
    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    if not probe_ok.wait(timeout=180.0):
        print("# default backend unresponsive; using cpu", file=sys.stderr)
        backend = "cpu"
        jax.config.update("jax_default_device", jax.devices("cpu")[0])

    import jax.numpy as jnp

    from garage_tpu.models.pipeline import ScrubRepairPipeline
    from garage_tpu.ops import gf

    k, m = args.k, args.m
    shard_bytes = args.block_bytes // k
    pipe = ScrubRepairPipeline(k=k, m=m, shard_bytes=shard_bytes)

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (args.batch, k, shard_bytes), dtype=np.uint8)
    dev = jax.devices(backend)[0] if backend else jax.devices()[0]
    data_dev = jax.device_put(jnp.asarray(data), dev)
    if args.verbose:
        print(f"# backend={dev.platform} device={dev}", file=sys.stderr)

    if args.hash and args.repair:
        ap.error("--hash and --repair are mutually exclusive")
    if args.hash:
        fn = pipe.jitted()

        def run(x):
            p, h, s = fn(x)
            return p
    elif args.repair:
        from garage_tpu.ops.ec_tpu import _apply_fn

        # lose the first m data shards; reconstruct from survivors
        present = list(range(m, k + m))
        rmat = gf.reconstruction_matrix(k, m, present[:k], list(range(m)))
        bitmat = jnp.asarray(gf.bitmatrix_of(rmat), dtype=jnp.bfloat16)
        apply_fn = _apply_fn(None)

        def run(x):
            return apply_fn(bitmat, x)
    else:
        from garage_tpu.ops.ec_tpu import _apply_fn

        bitmat = jnp.asarray(
            gf.bitmatrix_of(gf.cauchy_parity_matrix(k, m)), dtype=jnp.bfloat16
        )
        apply_fn = _apply_fn(None)

        def run(x):
            return apply_fn(bitmat, x)

    def sync(x):
        # On the tunneled axon platform block_until_ready can return before
        # execution finishes; a 1-byte host fetch is the honest barrier.
        np.asarray(x[(0,) * (x.ndim - 1)][:1])

    for _ in range(args.warmup):
        sync(run(data_dev))

    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = run(data_dev)
    sync(out)
    dt = time.perf_counter() - t0

    bytes_per_iter = args.batch * k * shard_bytes  # data bytes coded
    gbps = bytes_per_iter * args.iters / dt / 1e9
    metric = "ec%d%d_%s_GBps" % (k, m, "repair" if args.repair else "encode")
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / 10.0, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
