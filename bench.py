#!/usr/bin/env python3
"""Headline benchmark: EC(8,3) erasure-encode throughput per chip.

Runs the flagship fused pipeline (GF(2^8) coding of 1 MiB blocks) on the
default JAX backend and prints ONE JSON line:

    {"metric": "ec83_encode_GBps", "value": N, "unit": "GB/s",
     "vs_baseline": N / 10.0, "platform": "tpu"|"cpu"|"none"}

("platform" records which backend produced the number: the chip, the CPU
fallback, or "none" for the all-backends-failed sentinel line.)

Baseline (BASELINE.md north star): >= 10 GB/s EC(8,3) encode+repair on one
v5e chip.  `vs_baseline` > 1.0 means the target is beaten.

Flags: --batch (blocks per dispatch), --iters, --hash (also compute BLAKE3
shard hashes in the same dispatch), --repair (measure reconstruction of m
lost shards instead of encode).

Wedge-proofing, round-4 design (the tunneled TPU backend can wedge a
process forever, even during PJRT init; an in-process watchdog cannot
unwedge it; and rounds 1-3 showed a single 360 s do-everything child banks
NOTHING when any stage of it wedges).  The parent never imports jax and
runs a LADDER of short, independently-killable children:

  1. probe (60 s full + 2x45 s single-phase brackets): init the backend,
     one tiny matmul + host fetch.  A wedged tunnel dies here after
     ~150 s total (the brackets pin WHICH phase wedged), then straight
     to CPU fallback; a probe that exits quickly with an ordinary error
     (rc!=0, e.g. an ImportError) skips the brackets entirely and is
     labeled `failed`, not `wedged`.
  2. quick dial (150 s): small-batch measurement on the einsum path
     (plain XLA, no Mosaic remote-compile exposure) -> banks a first
     "platform": "tpu" line.
  3. flagship dial (240 s): full-batch fused Pallas kernel -> upgrades
     the banked number.  If it wedges, the step-2 number still stands.

Children enable the persistent XLA compilation cache (committed
`.xla_cache/` dir), so any process that finds a healthy window spends its
budget executing, not compiling — and pre-warms the cache for the next.
Every attempt (cmd, rc, stdout, stderr, UTC timestamps) is appended to
`tpu_runs/bench_<ts>.log` so on-chip claims are auditable after the fact.
"""

import argparse
import json
import os
import subprocess
import sys
import time

PROBE_TIMEOUT = 60.0
QUICK_TIMEOUT = 150.0
FLAGSHIP_TIMEOUT = 240.0
CPU_TIMEOUT = 270.0

REPO = os.path.dirname(os.path.abspath(__file__))


def parse_args(argv):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--m", type=int, default=3)
    ap.add_argument("--block-bytes", type=int, default=1024 * 1024)
    # Default blocks-per-dispatch is backend-dependent (resolved in
    # child_main): 2048 on an accelerator, 8 on CPU.  Measured on the v5e
    # (2026-07-29), encode rate climbs with batch as dispatch/tunnel
    # overhead amortizes — 64->21.4, 128->36.4, 256->52.1, 512->67.7,
    # 1024->79.6, 2048->86.6, 4096->91.7 GB/s; 2048 is within 6% of the
    # 4 GiB-batch rate at half the HBM footprint.  On CPU a 2 GiB batch
    # would OOM/time-out the 1-core box, hence the per-backend default.
    ap.add_argument("--batch", type=int, default=None, help="blocks per dispatch")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--hash", action="store_true", help="fuse BLAKE3 shard hashing")
    ap.add_argument("--repair", action="store_true", help="bench reconstruction")
    ap.add_argument("--impl", choices=["pallas_int8", "pallas_bf16", "einsum"],
                    default=None, help="pin the EC kernel implementation")
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--no-ladder", action="store_true",
                    help="single child on the default backend (old behavior)")
    ap.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--_probe", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--_probe_phase", default="dispatch", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.hash and args.repair:
        ap.error("--hash and --repair are mutually exclusive")
    return args


def probe_main(phase: str = "dispatch") -> None:
    """Phase-stamped backend liveness check (VERDICT r4 ask #1).

    Prints a flushed timestamped JSON line after each phase so that even
    when the parent hard-kills a wedged child, the partial pipe output
    pins WHICH phase wedged:

      import   — interpreter start + `import jax` (plugin registration;
                 the axon sitecustomize dials the tunnel at interp start)
      devices  — `jax.devices()` (PJRT client init + device enumeration)
      dispatch — 16-byte jit dispatch + host fetch (executor round-trip)

    `phase` stops early, letting the parent bracket a wedge with shorter
    single-phase children when the full probe times out.
    """
    t0 = time.time()

    def stamp(name):
        print(json.dumps({"phase": name, "t": round(time.time() - t0, 3)}),
              flush=True)

    from garage_tpu.utils.compile_cache import enable_persistent_cache

    import jax  # noqa: F401 — plugin registration side effect

    stamp("import")
    if phase == "import":
        return
    enable_persistent_cache()
    devs = jax.devices()
    stamp("devices")
    if phase == "devices":
        print(json.dumps({"probe": "devices-ok",
                          "platform": devs[0].platform}), flush=True)
        return
    import numpy as np

    import jax.numpy as jnp

    x = jnp.arange(16, dtype=jnp.uint8)  # 16-byte dispatch
    y = jax.jit(lambda a: a + 1)(x)
    np.asarray(y[:1])  # honest host-fetch barrier
    stamp("dispatch")
    print(json.dumps({"probe": "ok", "platform": devs[0].platform,
                      "device": str(devs[0])}))


def phased_probe(env, transcript=None):
    """Run the liveness probe with per-phase wedge attribution.

    Full probe first (60 s).  On success returns its final JSON line.  On
    wedge/failure, runs shorter single-phase children to bracket where the
    backend stalls, then writes `tpu_runs/probe_profile_<ts>.json` — the
    committed per-phase wedge profile VERDICT r4 asked for — and returns
    None.  The profile carries a structured `failure_reason` ({phase, rc,
    timed_out, dt, stderr_tail}) taken from the bracket child that
    targeted the wedged phase (ISSUE 11): `BENCH_r05.json`'s probe has
    wedged at `devices` for six rounds with zero evidence of WHY, because
    the killed child's stderr died with its pipe.
    """
    me = os.path.abspath(__file__)

    def run_phase(phase, timeout):
        cmd = [sys.executable, me, "--_probe", "--_probe_phase", phase]
        rc, out, err, dt = run_logged(cmd, timeout, env=env)
        if transcript:
            transcript.record(f"probe-{phase}", cmd, rc, out, err, dt)
        stamps = [l for l in json_lines(out) if "phase" in l]
        final = [l for l in json_lines(out) if "probe" in l]
        # the child's stderr tail rides the artifact: five rounds of
        # "wedged at devices" taught nothing because the PJRT/plugin
        # noise that says WHY died with the killed child's pipe
        return {"phase_arg": phase, "rc": rc, "dt": round(dt, 1),
                "stamps": stamps, "final": final[-1] if final else None,
                "stderr_tail": (err or "")[-2000:]}

    full = run_phase("dispatch", PROBE_TIMEOUT)
    if full["rc"] == 0 and full["final"] and full["final"].get("probe") == "ok":
        return full["final"]

    profile = {"utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
               "full": full}
    # A child that exits QUICKLY with an ordinary error (rc != 0 — an
    # ImportError, a plugin crash) is not a wedge: the ~90 s of bracket
    # children would only re-confirm the same error and the resulting
    # "wedged_at" profile would be a lie.  Brackets are for wedges only
    # (TIMEOUT, or a kill that ate most of the budget).
    fast_error = (
        full["rc"] not in (0, "TIMEOUT") and full["dt"] < PROBE_TIMEOUT / 2
    )

    def reason_from(attempt, phase):
        """Structured failure evidence from one probe child: what the
        next (human or agent) TPU session needs to DIAGNOSE the stuck
        phase instead of re-running the whole ladder blind."""
        return {
            "phase": phase,
            "rc": attempt["rc"],
            "timed_out": attempt["rc"] == "TIMEOUT",
            "dt": attempt["dt"],
            "stderr_tail": attempt.get("stderr_tail", ""),
        }

    if fast_error:
        profile["result"] = "failed"
        profile["wedged_at"] = None
        profile["failure_reason"] = reason_from(full, "full")
    else:
        profile["result"] = "wedged"
        profile["brackets"] = [run_phase("import", 45), run_phase("devices", 45)]
        reached = [s["phase"] for s in full["stamps"]]
        order = ["import", "devices", "dispatch"]
        profile["wedged_at"] = next(
            (p for p in order if p not in reached), "after-dispatch"
        )
        # prefer the single-phase bracket child that targeted the wedged
        # phase (its stderr is the devices-phase PJRT/tunnel evidence the
        # BENCH_r05 probe never surfaced); fall back to the full run
        culprit = next(
            (b for b in profile["brackets"]
             if b["phase_arg"] == profile["wedged_at"]),
            full,
        )
        profile["failure_reason"] = reason_from(
            culprit, profile["wedged_at"]
        )
    d = os.path.join(REPO, "tpu_runs")
    os.makedirs(d, exist_ok=True)
    ts = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
    path = os.path.join(d, f"probe_profile_{ts}.json")
    with open(path, "w") as f:
        json.dump(profile, f, indent=1)
    if fast_error:
        print(f"# probe failed fast (rc={full['rc']}, {full['dt']}s); "
              f"profile -> {path}", file=sys.stderr)
    else:
        print(f"# probe wedged at phase '{profile['wedged_at']}'; "
              f"profile -> {path}", file=sys.stderr)
    return None


def prelower_kernels(args, dev) -> None:
    """AOT-compile (jit(...).lower().compile()) the EC coding kernel AND
    the fused encode+BLAKE3 pipeline for the production shape into the
    persistent XLA cache (VERDICT r5 Missing #5 / ask #8).

    Runs at bench startup on accelerator backends regardless of which
    dial this process is measuring: the encode dial usually wins the
    first healthy window, and pre-lowering here banks the compiled hash
    kernel so a FUTURE on-chip `bench.py --hash --batch 2048` spends its
    600 s window executing, not compiling.  Failures are advisory — the
    dial's own path compiles lazily as before.  (Skipped on CPU unless
    GARAGE_PRELOWER=1: the 2048-batch fused kernel takes minutes to
    compile there and the persistent cache is disabled anyway.)"""
    if dev.platform == "cpu" and os.environ.get("GARAGE_PRELOWER") != "1":
        return
    import time as _time

    t0 = _time.time()
    try:
        import jax
        import jax.numpy as jnp

        from garage_tpu.models.pipeline import ScrubRepairPipeline
        from garage_tpu.ops.ec_tpu import _ec_body

        k, m = args.k, args.m
        shard = args.block_bytes // k
        batch = 2048  # the production on-chip dial shape
        bit = jax.ShapeDtypeStruct((8 * m, 8 * k), jnp.uint8)
        x = jax.ShapeDtypeStruct((batch, k, shard), jnp.uint8)
        # one EC shape serves encode AND m-rank reconstruction (the
        # coding matrix is a traced argument, same compiled kernel)
        jax.jit(_ec_body(dev.platform, args.impl)).lower(bit, x).compile()
        pipe = ScrubRepairPipeline(k=k, m=m, shard_bytes=shard)
        jax.jit(pipe.encode_and_hash_fn()).lower(x).compile()
        print(f"# prelower: EC + fused encode+hash kernels cached in "
              f"{_time.time() - t0:.1f}s", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — advisory only
        print(f"# prelower skipped: {type(e).__name__}: {e}", file=sys.stderr)


def codec_xray_detail(k, m, shard_bytes) -> dict:
    """The `detail.codec` block bench_diff floors check (ISSUE 17):
    drive a short instrumented section through the PRODUCTION codec
    dispatch path — ops/ec_tpu.EcTpu fused encode+hash (odd batch sizes
    so pow2 bucketing actually pads) plus a mini codec-batcher session
    (lane linger + flush attribution) — then reduce the process-wide
    ops/telemetry.codec_snapshot to the banked scalars.  The timed loop
    above calls jitted functions directly (measurement must not pay
    observatory overhead), so this section is what makes the X-ray
    numbers appear in the artifact at all."""
    import asyncio

    import numpy as np

    from garage_tpu.ops import telemetry
    from garage_tpu.ops.ec_tpu import EcTpu
    from garage_tpu.utils.metrics import registry

    shard = min(shard_bytes, 4096)
    ec = EcTpu(k, m)
    rng = np.random.default_rng(1)
    for b in (3, 5):  # pow2 buckets pad 3->4 and 5->8: waste 0.25, 0.375
        ec.encode_and_hash(
            rng.integers(0, 256, (b, k, shard), dtype=np.uint8)
        )

    async def lane_session():
        from garage_tpu.block.codec.ec import EcCodec
        from garage_tpu.block.codec_batch import CodecBatcher

        batcher = CodecBatcher(EcCodec(k, m), linger_msec=2.0)
        try:
            payload = bytes(rng.integers(0, 256, k * 256, dtype=np.uint8))
            await asyncio.gather(
                *(batcher.encode(payload) for _ in range(8))
            )
        finally:
            await batcher.close()

    asyncio.run(lane_session())
    snap = telemetry.codec_snapshot(registry)
    return {
        "pad_waste": snap["padWaste"],
        "compile_events": snap["compileEvents"],
        "compile_secs": snap["compileSecs"],
        "overlap_efficiency": snap["overlapEfficiency"],
        "lane_linger_p99": snap["laneLingerP99"],
    }


def child_main(args) -> None:
    """Measurement body — runs in a subprocess the parent can hard-kill."""
    from garage_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from garage_tpu.models.pipeline import ScrubRepairPipeline
    from garage_tpu.ops import gf

    k, m = args.k, args.m
    shard_bytes = args.block_bytes // k
    pipe = ScrubRepairPipeline(k=k, m=m, shard_bytes=shard_bytes)

    dev = jax.devices()[0]
    prelower_kernels(args, dev)
    if args.batch is None:
        args.batch = 8 if dev.platform == "cpu" else 2048
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (args.batch, k, shard_bytes), dtype=np.uint8)
    data_dev = jax.device_put(jnp.asarray(data), dev)
    if args.verbose:
        print(f"# backend={dev.platform} device={dev}", file=sys.stderr)

    def sync(x):
        # On the tunneled axon platform block_until_ready can return before
        # execution finishes; a 1-byte host fetch is the honest barrier.
        np.asarray(x[(0,) * (x.ndim - 1)][:1])

    if args.hash:
        fn = pipe.jitted()

        def run(x):
            p, h, s = fn(x)
            return p

        sync(run(data_dev))  # warmup/compile
    else:
        from garage_tpu.ops.ec_tpu import ec_apply_fn

        if args.repair:
            # lose the first m data shards; reconstruct from survivors
            present = list(range(m, k + m))
            mat = gf.reconstruction_matrix(k, m, present[:k], list(range(m)))
        else:
            mat = gf.cauchy_parity_matrix(k, m)
        bitmat = jax.device_put(jnp.asarray(gf.bitmatrix_of(mat), jnp.uint8), dev)

        # Try the fused Pallas kernel first; fall back to the portable
        # einsum path if the backend can't lower it.  On CPU the native
        # C++ LUT codec is the framework's real encode path (the Pallas
        # kernel only exists in interpreter mode there).
        if args.impl:
            impls = [args.impl]
        elif dev.platform == "cpu":
            impls = ["native", "einsum"]
        else:
            impls = ["pallas_int8", "pallas_bf16", "einsum"]
        run = None
        for impl in impls:
            if impl == "native":
                from garage_tpu import _native

                if _native.available():
                    def run(x, _mat=mat, _np=data):
                        for b in range(_np.shape[0]):
                            out = _native.gf8_apply(_mat, _np[b])
                        return out

                    if args.verbose:
                        print("# impl=native (C++ host codec)", file=sys.stderr)
                    break
                continue
            try:
                apply_fn = ec_apply_fn(None, impl)
                out = apply_fn(bitmat, data_dev)
                sync(out)
            except Exception as e:  # noqa: BLE001 — try next impl
                print(f"# impl {impl} failed: {type(e).__name__}: {e}",
                      file=sys.stderr)
                continue
            if args.verbose:
                print(f"# impl={impl}", file=sys.stderr)

            def run(x, _fn=apply_fn):
                return _fn(bitmat, x)

            break
        if run is None:
            raise RuntimeError("no EC impl usable on this backend")

    for _ in range(args.warmup):
        sync(run(data_dev))

    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = run(data_dev)
    sync(out)
    dt = time.perf_counter() - t0

    bytes_per_iter = args.batch * k * shard_bytes  # data bytes coded
    gbps = bytes_per_iter * args.iters / dt / 1e9
    metric = "ec%d%d_%s_GBps" % (k, m, "repair" if args.repair else "encode")
    if args.hash:
        metric = "ec%d%d_encode_hash_GBps" % (k, m)
    # codec X-ray detail (ISSUE 17) — advisory: a broken observatory
    # must not cost the banked throughput number
    try:
        codec_detail = codec_xray_detail(k, m, shard_bytes)
    except Exception as e:  # noqa: BLE001 — advisory only
        print(f"# codec x-ray section failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        codec_detail = None

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / 10.0, 4),
                "platform": dev.platform,
                "batch": args.batch,
                "detail": {"codec": codec_detail},
            }
        )
    )


class Transcript:
    """Appends every child attempt to tpu_runs/bench_<ts>.log (auditable
    raw record of on-chip runs — VERDICT r3 Weak #2)."""

    def __init__(self):
        ts = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
        d = os.path.join(REPO, "tpu_runs")
        os.makedirs(d, exist_ok=True)
        self.path = os.path.join(d, f"bench_{ts}.log")
        self._write(f"# bench.py ladder transcript — started {ts}Z\n"
                    f"# argv: {sys.argv[1:]}\n")

    def _write(self, s):
        with open(self.path, "a") as f:
            f.write(s)

    def record(self, stage, cmd, rc, out, err, dt):
        now = time.strftime("%H:%M:%S", time.gmtime())
        self._write(
            f"\n== {stage} @ {now}Z rc={rc} dt={dt:.1f}s\n"
            f"$ {' '.join(cmd)}\n"
            + "".join(f"O| {l}\n" for l in (out or "").splitlines())
            + "".join(f"E| {l}\n" for l in (err or "").splitlines())
        )


def run_logged(cmd, timeout, env=None, cwd=REPO):
    """Subprocess with a hard timeout.  Returns (rc, stdout, stderr, dt);
    rc is "TIMEOUT" on expiry (partial output preserved).  Shared with
    script/tpu_bank.py so the wedge-handling exists exactly once."""
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, env=env, cwd=cwd, timeout=timeout,
            capture_output=True, text=True,
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = "TIMEOUT"
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
    return rc, out, err, time.time() - t0


def json_lines(text):
    """Every parseable {...} line in `text`, in order."""
    res = []
    for line in (text or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                res.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return res


def run_child(argv, env, timeout, transcript=None, stage=""):
    """Run a measurement subprocess; return its JSON line or None."""
    cmd = [sys.executable, os.path.abspath(__file__), *argv]
    rc, out, err, dt = run_logged(cmd, timeout, env=env)
    if transcript:
        transcript.record(stage or "child", cmd, rc, out, err, dt)
    if rc == "TIMEOUT":
        print(f"# bench {stage or 'child'} timed out after {timeout:.0f}s "
              "(backend wedged?)", file=sys.stderr)
        return None
    sys.stderr.write(err)
    lines = json_lines(out)
    if lines:
        return lines[0]
    print(f"# bench {stage or 'child'} rc={rc}, no JSON line", file=sys.stderr)
    return None


def cpu_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the sitecustomize dials the TPU tunnel at interpreter startup
    # when this is set — scrub it so the CPU child can never block
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def main() -> None:
    argv = sys.argv[1:]
    args = parse_args(argv)
    if args._probe:
        probe_main(args._probe_phase)
        return
    if args._child:
        child_main(args)
        return

    tr = Transcript()
    env = dict(os.environ)
    result = None
    argv = [a for a in argv if a != "--no-ladder"]

    # Step 1: phase-stamped canary.  A wedged tunnel dies here (with a
    # committed per-phase wedge profile), not at 360 s.
    probe = phased_probe(env, tr)
    tpu_ok = bool(probe) and probe.get("platform") not in (None, "cpu")

    if tpu_ok and not args.no_ladder:
        # Step 2: bank a first TPU number on the lowest-risk path.
        # (Skipped when the user pinned impl/batch — they asked for one dial.)
        if args.impl is None and args.batch is None and not args.hash:
            quick_argv = ["--_child", *argv, "--impl", "einsum",
                          "--batch", "64", "--iters", "10"]
            result = run_child(quick_argv, env, QUICK_TIMEOUT, tr, "quick-einsum")
            if result and result.get("platform") == "cpu":
                result = None  # don't let a mis-routed child masquerade as tpu

        # Step 3: flagship fused-Pallas dial; upgrades the banked number.
        flag = run_child(["--_child", *argv], env, FLAGSHIP_TIMEOUT, tr, "flagship")
        if flag and flag.get("platform") != "cpu":
            if result is None or flag.get("value", 0) >= result.get("value", 0):
                result = flag
    elif tpu_ok:
        result = run_child(["--_child", *argv], env, FLAGSHIP_TIMEOUT, tr, "single")
        if result and result.get("platform") == "cpu":
            pass  # user forced something odd; keep it

    if result is None:
        # CPU fallback in a fresh process — the wedged plugin is never
        # even initialized.  Scale shapes down unless the user pinned them.
        cpu_argv = ["--_child", *argv]
        if args.batch is None:
            cpu_argv += ["--batch", "8"]
        if "--iters" not in " ".join(argv):
            # long enough that scheduler noise on the 1-CPU box doesn't
            # dominate (5 iters = ~80 ms of work; 40 = ~1.5 s)
            cpu_argv += ["--iters", "40"]
        print("# default backend unusable; falling back to cpu", file=sys.stderr)
        result = run_child(cpu_argv, cpu_env(), CPU_TIMEOUT, tr, "cpu-fallback")

    if result is None:
        # Last resort: still emit a parseable line; value 0 = failed run.
        dial = "repair" if args.repair else (
            "encode_hash" if args.hash else "encode")
        metric = "ec%d%d_%s_GBps" % (args.k, args.m, dial)
        result = {
            "metric": metric,
            "value": 0.0,
            "unit": "GB/s",
            "vs_baseline": 0.0,
            "platform": "none",
            "error": "all backends failed or timed out",
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
