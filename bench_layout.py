#!/usr/bin/env python3
"""Layout-transition benchmark: grow a live EC cluster and bank what the
rebalance observatory (rpc/transition.py, doc/monitoring.md §"Rebalance
observatory") measured about it.

Boots an in-process EC cluster with the first `--base` nodes in the
layout, seeds objects through the real S3 API, then stages the remaining
`--grow` nodes and applies — opening a genuine layout transition that
the per-node `TransitionTracker`s narrate while background workers sync
and retire the old version.  The banked artifact is the observatory's
own output: transition duration, bytes attributed to (src → dst) pairs,
the final sync fraction, and the structured transition-report — so
`script/bench_diff.py` floors catch the observatory (or the migration
plane under it) silently breaking.

Prints ONE JSON line and (with --artifact) commits it:

    {"metric": "layout_transition_s", "value": T, "unit": "s",
     "bytes_moved": B, "pairs": P, "sync_fraction_final": 1.0, ...}

Usage: python bench_layout.py [--base 5 --grow 2] [--artifact F]
"""

import argparse
import asyncio
import json
import os
import pathlib
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--base", type=int, default=7,
                    help="nodes in the initial layout")
    ap.add_argument("--grow", type=int, default=2,
                    help="nodes added by the transition")
    ap.add_argument("--mode", default="ec:4:2")
    ap.add_argument("--objects", type=int, default=48)
    ap.add_argument("--object-bytes", type=int, default=20_000)
    ap.add_argument("--timeout", type=float, default=360.0,
                    help="seconds to wait for the transition to close")
    ap.add_argument("--artifact", help="also write the JSON result here")
    ap.add_argument("--verbose", action="store_true")
    return ap.parse_args(argv)


def vlog(args, msg):
    if args.verbose:
        print(f"# {msg}", file=sys.stderr)


async def run_bench(args, tmp):
    from test_ec_cluster import make_ec_cluster, stop_cluster

    from garage_tpu.api.s3.api_server import S3ApiServer
    from garage_tpu.api.s3.client import S3Client
    from garage_tpu.rpc.layout.types import NodeRole
    from garage_tpu.rpc.transition import cluster_events_response

    n = args.base + args.grow
    garages = await make_ec_cluster(
        tmp, n=n, mode=args.mode, assign=set(range(args.base))
    )
    s3 = S3ApiServer(garages[0])
    await s3.start("127.0.0.1", 0)
    ep = f"http://127.0.0.1:{s3.runner.addresses[0][1]}"
    key = await garages[0].helper.create_key("bench-layout")
    key.params().allow_create_bucket.update(True)
    await garages[0].key_table.insert(key)
    client = S3Client(ep, key.key_id, key.secret())
    try:
        await client.create_bucket("bench")
        bodies = {}
        for i in range(args.objects):
            k = f"obj-{i:04d}"
            bodies[k] = f"{i}:".encode() + os.urandom(args.object_bytes)
            await client.put_object("bench", k, bodies[k])
        vlog(args, f"seeded {args.objects} objects on {args.base} nodes")

        lm = garages[0].layout_manager
        for i in range(args.base, n):
            lm.stage_role(
                garages[i].node_id, NodeRole(zone=f"dc{i}", capacity=10**12)
            )
        t0 = time.perf_counter()
        lm.apply_staged()

        deadline = t0 + args.timeout
        closed_s = None
        while time.perf_counter() < deadline:
            await asyncio.sleep(0.25)
            if all(
                not g.transition_tracker.active
                and g.transition_tracker.sync_fraction() == 1.0
                and g.transition_tracker.reports > 0
                for g in garages
            ):
                closed_s = time.perf_counter() - t0
                break
        if closed_s is None:
            frac = [g.transition_tracker.sync_fraction() for g in garages]
            raise RuntimeError(
                f"transition did not close within {args.timeout}s "
                f"(sync fractions: {frac})"
            )
        vlog(args, f"transition closed in {closed_s:.1f}s")

        # read-back after the move: every object survives the grow
        for k, body in bodies.items():
            got = await client.get_object("bench", k)
            if got != body:
                raise RuntimeError(f"{k}: corrupted after the transition")

        # aggregate the per-node reports (each report's bytesMoved must
        # equal its own pair counters — the acceptance invariant)
        reports = [
            g.transition_tracker.last_report
            for g in garages
            if g.transition_tracker.last_report is not None
        ]
        for rep in reports:
            pair_sum = sum(p["bytes"] for p in rep["pairs"])
            if rep["bytesMoved"] != pair_sum:
                raise RuntimeError(
                    f"report bytesMoved {rep['bytesMoved']} != "
                    f"pair sum {pair_sum}"
                )
        bytes_moved = sum(r["bytesMoved"] for r in reports)
        pairs = sum(len(r["pairs"]) for r in reports)
        duration_max = max(r["durationSecs"] for r in reports)

        ev = await cluster_events_response(garages[0], since=0.0)
        frac_final = min(
            g.transition_tracker.sync_fraction() for g in garages
        )
        return {
            "metric": "layout_transition_s",
            "value": round(closed_s, 2),
            "unit": "s",
            "layout_transition_s": round(closed_s, 2),
            "transition_s": round(closed_s, 2),
            "report_duration_max_s": round(duration_max, 2),
            "bytes_moved": int(bytes_moved),
            "pairs": pairs,
            "reports": len(reports),
            "sync_fraction_final": frac_final,
            "events_nodes_responding": len(ev["nodesResponding"]),
            "events_nodes_failed": len(ev["nodesFailed"]),
            "timeline_events": len(ev["events"]),
            "objects": args.objects,
            "object_bytes": args.object_bytes,
            "mode": args.mode,
            "nodes_before": args.base,
            "nodes_after": n,
            "utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        }
    finally:
        await stop_cluster(garages, [s3], [client])


def main(argv=None):
    args = parse_args(argv)
    with tempfile.TemporaryDirectory(prefix="bench_layout_") as tmp:
        result = asyncio.run(run_bench(args, pathlib.Path(tmp)))
    print(json.dumps(result))
    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
